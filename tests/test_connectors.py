"""Connector + format suite: serde formats, filesystem sink two-phase
commit, transactional kafka (in-memory broker), and the HTTP-family sources
against real local aiohttp servers — mirroring the reference's connector
tests which drive a real local service and inject control messages by hand
(kafka/source/test.rs:28-100)."""

import asyncio
import base64
import json

import numpy as np
import pytest

from arroyo_tpu import Batch, Stream
from arroyo_tpu.connectors.kafka import InMemoryKafkaBroker
from arroyo_tpu.connectors.memory import clear_sink, sink_output
from arroyo_tpu.engine.engine import Engine, LocalRunner
from arroyo_tpu.formats import (
    JsonFormat,
    RawStringFormat,
    batch_from_rows,
    json_schema_for_rows,
    make_format,
)
from arroyo_tpu.types import StopMode


# ---------------------------------------------------------------------------
# formats
# ---------------------------------------------------------------------------


def test_json_format_roundtrip():
    fmt = JsonFormat()
    rows = [{"a": 1, "b": "x"}, {"a": 2, "b": "y"}]
    payloads = fmt.serialize(rows)
    assert fmt.deserialize(payloads) == rows
    batch = fmt.batch(payloads)
    assert batch.columns["a"].dtype == np.int64
    assert list(batch.columns["b"]) == ["x", "y"]


def test_json_null_timestamp_falls_back_without_latching():
    """One payload missing the timestamp field must row-path THAT batch
    (no NaN->int64 undefined behavior) and keep the columnar fast path
    for subsequent well-formed batches (advisor r3 finding + review)."""
    fmt = JsonFormat()
    good = [json.dumps({"ts": 10 + i, "v": i}).encode() for i in range(3)]
    bad = good[:2] + [json.dumps({"v": 99}).encode()]
    b1 = fmt.batch(bad, timestamp_field="ts")
    assert len(b1) == 3  # row path handled the missing field explicitly
    assert getattr(fmt, "_arrow_ok", True), "fast path must not latch off"
    b2 = fmt.batch(good, timestamp_field="ts")
    assert b2.timestamp.tolist() == [10, 11, 12]
    assert b2.timestamp.dtype == np.int64


def test_json_confluent_header_strip():
    fmt = JsonFormat(confluent_schema_registry=True)
    payload = b"\x00\x00\x00\x00\x07" + json.dumps({"v": 42}).encode()
    assert fmt.deserialize([payload]) == [{"v": 42}]


def test_json_unstructured():
    fmt = JsonFormat(unstructured=True)
    rows = fmt.deserialize([b'{"not": "parsed"}'])
    assert rows == [{"value": '{"not": "parsed"}'}]


def test_debezium_unwrap():
    fmt = make_format("debezium_json")
    create = json.dumps({"payload": {
        "before": None, "after": {"id": 1, "v": "a"}, "op": "c"}}).encode()
    update = json.dumps({"payload": {
        "before": {"id": 1, "v": "a"}, "after": {"id": 1, "v": "b"},
        "op": "u"}}).encode()
    delete = json.dumps({"payload": {
        "before": {"id": 1, "v": "b"}, "after": None, "op": "d"}}).encode()
    rows = fmt.deserialize([create, update, delete])
    ops = [r["__op"] for r in rows]
    assert ops == ["append", "retract", "append", "retract"]
    assert rows[2]["v"] == "b"


def test_raw_string_format():
    fmt = RawStringFormat()
    assert fmt.deserialize([b"hello"]) == [{"value": "hello"}]
    assert fmt.serialize([{"value": "bye"}]) == [b"bye"]


def test_json_schema_inference():
    schema = json_schema_for_rows([{"a": 1, "b": "s", "c": 1.5, "d": True}])
    props = schema["properties"]
    assert props["a"]["type"] == "integer"
    assert props["b"]["type"] == "string"
    assert props["c"]["type"] == "number"
    assert props["d"]["type"] == "boolean"


def test_include_schema_envelope():
    fmt = JsonFormat(include_schema=True)
    [payload] = fmt.serialize([{"a": 1}])
    env = json.loads(payload)
    assert set(env) == {"schema", "payload"}
    assert fmt.deserialize([payload]) == [{"a": 1}]


# ---------------------------------------------------------------------------
# filesystem sink
# ---------------------------------------------------------------------------


def test_filesystem_sink_graceful_json(tmp_path):
    out = tmp_path / "fs_out"
    prog = (Stream.source("impulse", {"event_rate": 0.0, "message_count": 100,
                                      "batch_size": 32})
            .map(lambda c: {"counter": c["counter"]}, name="id")
            .sink("filesystem", {"path": f"file://{out}", "format": "json"}))
    LocalRunner(prog).run()
    parts = sorted(out.glob("part-*.json"))
    assert parts, f"no parts in {list(out.iterdir()) if out.exists() else []}"
    rows = [json.loads(l) for p in parts for l in open(p)]
    assert sorted(r["counter"] for r in rows) == list(range(100))
    assert not list(out.glob(".staging/*"))


def test_filesystem_sink_parquet(tmp_path):
    import pyarrow.parquet as pq

    out = tmp_path / "fs_parquet"
    prog = (Stream.source("impulse", {"event_rate": 0.0, "message_count": 64,
                                      "batch_size": 16})
            .map(lambda c: {"counter": c["counter"],
                            "sq": c["counter"] ** 2}, name="sq")
            .sink("filesystem", {"path": f"file://{out}",
                                 "format": "parquet"}))
    LocalRunner(prog).run()
    parts = sorted(out.glob("part-*.parquet"))
    assert parts
    table = pq.read_table(parts[0])
    assert sorted(table.column("counter").to_pylist()) == list(range(64))


def test_filesystem_two_phase_commit_visibility(tmp_path):
    """Parts staged at a checkpoint become visible only after the commit
    phase — and a crash before commit leaves no final parts behind."""
    out = tmp_path / "fs_2pc"
    url = f"file://{tmp_path}/ckpt"

    def build():
        return (Stream.source("impulse", {
                    "event_rate": 500_000.0, "message_count": 200_000,
                    "batch_size": 256})
                .map(lambda c: {"counter": c["counter"]}, name="id")
                .sink("filesystem", {"path": f"file://{out}",
                                     "format": "json"}))

    async def run():
        eng = Engine.for_local(build(), "fs2pc-job", checkpoint_url=url)
        running = eng.start()
        await asyncio.sleep(0.05)
        await running.checkpoint(1)
        assert await running.wait_for_checkpoint(1)
        staged = list(out.glob(".staging/part-*"))
        finals = list(out.glob("part-*"))
        assert staged and not finals, (staged, finals)
        await running.commit(1)
        await asyncio.sleep(0.05)
        finals = list(out.glob("part-*"))
        assert finals, "commit did not promote staged parts"
        await running.stop(StopMode.IMMEDIATE)
        try:
            await running.join()
        except RuntimeError:
            pass

    asyncio.run(run())


# ---------------------------------------------------------------------------
# kafka (in-memory broker)
# ---------------------------------------------------------------------------


def test_kafka_source_to_memory_sink():
    InMemoryKafkaBroker.reset("t1")
    broker = InMemoryKafkaBroker.get("t1")
    broker.create_topic("events", partitions=2)
    for i in range(100):
        broker.produce("events", json.dumps({"i": i}).encode(), partition=i % 2)

    clear_sink("k1")
    prog = (Stream.source("kafka", {"bootstrap_servers": "memory://t1",
                                    "topic": "events", "max_messages": 100})
            .map(lambda c: {"i": c["i"]}, name="id")
            .sink("memory", {"name": "k1"}))
    LocalRunner(prog).run()
    rows = Batch.concat(sink_output("k1"))
    assert sorted(rows.columns["i"].tolist()) == list(range(100))


def test_kafka_source_offset_resume(tmp_path):
    """Checkpoint mid-stream, crash, restore: offsets resume so every record
    is read exactly once (kafka/source/test.rs pattern)."""
    InMemoryKafkaBroker.reset("t2")
    broker = InMemoryKafkaBroker.get("t2")
    broker.create_topic("ev", partitions=1)
    for i in range(60):
        broker.produce("ev", json.dumps({"i": i}).encode(), partition=0)

    url = f"file://{tmp_path}/ckpt"
    clear_sink("k2")

    def build(maxm):
        return (Stream.source("kafka", {
                    "bootstrap_servers": "memory://t2", "topic": "ev",
                    "batch_size": 10, "max_messages": maxm})
                .sink("memory", {"name": "k2"}))

    # run 1: read all 60 messages, checkpoint epoch 1, stop
    async def run1():
        eng = Engine.for_local(build(None), "kafka-job", checkpoint_url=url)
        running = eng.start()
        # wait until the sink saw >= 30 rows
        for _ in range(200):
            got = sum(len(b) for b in sink_output("k2"))
            if got >= 30:
                break
            await asyncio.sleep(0.01)
        await running.checkpoint(1)
        assert await running.wait_for_checkpoint(1)
        await running.stop(StopMode.IMMEDIATE)
        try:
            await running.join()
        except RuntimeError:
            pass

    asyncio.run(run1())
    seen_before = {r for b in sink_output("k2")
                   for r in b.columns["i"].tolist()}
    assert seen_before  # run 1 made progress before the checkpoint
    clear_sink("k2")

    # new records arrive while the job is down
    for i in range(60, 120):
        broker.produce("ev", json.dumps({"i": i}).encode(), partition=0)

    async def run2():
        eng = Engine.for_local(build(None), "kafka-job", checkpoint_url=url,
                               restore_epoch=1)
        running = eng.start()
        for _ in range(300):
            got = {r for b in sink_output("k2")
                   for r in b.columns["i"].tolist()}
            if seen_before | got >= set(range(120)):
                break
            await asyncio.sleep(0.01)
        await running.stop(StopMode.IMMEDIATE)
        try:
            await running.join()
        except RuntimeError:
            pass

    asyncio.run(run2())
    seen_after = {r for b in sink_output("k2")
                  for r in b.columns["i"].tolist()}
    # no gaps across both runs, and nothing checkpointed as consumed in run 1
    # is re-read after restore (exactly-once resume)
    assert seen_before | seen_after == set(range(120))
    assert not (seen_before & seen_after)


def test_kafka_transactional_sink_read_committed():
    """Rows produced by the sink are invisible to read_committed consumers
    until the commit phase runs."""
    InMemoryKafkaBroker.reset("t3")
    broker = InMemoryKafkaBroker.get("t3")
    broker.create_topic("out", partitions=1)

    def build():
        return (Stream.source("impulse", {
                    "event_rate": 200_000.0, "message_count": 100_000,
                    "batch_size": 128})
                .map(lambda c: {"counter": c["counter"]}, name="id")
                .sink("kafka", {"bootstrap_servers": "memory://t3",
                                "topic": "out"}))

    async def run():
        eng = Engine.for_local(build(), "ksink-job")
        running = eng.start()
        await asyncio.sleep(0.05)
        await running.checkpoint(1)
        assert await running.wait_for_checkpoint(1)
        committed = broker.fetch("out", 0, 0, 10, read_committed=True)
        assert committed == []  # txn sealed but not committed
        await running.commit(1)
        await asyncio.sleep(0.05)
        committed = broker.fetch("out", 0, 0, 1_000_000, read_committed=True)
        assert len(committed) > 0
        await running.stop(StopMode.IMMEDIATE)
        try:
            await running.join()
        except RuntimeError:
            pass

    asyncio.run(run())


# ---------------------------------------------------------------------------
# HTTP family against live local servers
# ---------------------------------------------------------------------------


class _AiohttpServers:
    """Runs aiohttp apps on ephemeral ports inside the test's own event
    loop; tests must ``await srv.cleanup()`` before their loop closes."""

    def __init__(self):
        self._runners = []

    async def start(self, app):
        import aiohttp.web as web

        runner = web.AppRunner(app)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        port = site._server.sockets[0].getsockname()[1]
        self._runners.append(runner)
        return f"http://127.0.0.1:{port}"

    async def cleanup(self):
        for r in self._runners:
            await r.cleanup()
        self._runners.clear()


@pytest.fixture
def aiohttp_servers():
    return _AiohttpServers()


def test_polling_http_source(aiohttp_servers):
    import aiohttp.web as web

    count = {"n": 0}

    async def handler(request):
        count["n"] += 1
        return web.json_response({"n": count["n"]})

    async def run():
        app = web.Application()
        app.router.add_get("/poll", handler)
        base = await aiohttp_servers.start(app)

        clear_sink("http1")
        prog = (Stream.source("polling_http", {
                    "endpoint": f"{base}/poll", "poll_interval_ms": 1,
                    "max_polls": 5})
                .sink("memory", {"name": "http1"}))
        eng = Engine.for_local(prog, "poll-job")
        running = eng.start()
        try:
            await running.join()
        finally:
            await aiohttp_servers.cleanup()

    asyncio.run(run())
    rows = Batch.concat(sink_output("http1"))
    assert rows.columns["n"].tolist() == [1, 2, 3, 4, 5]


def test_sse_source(aiohttp_servers):
    import aiohttp.web as web

    async def sse_handler(request):
        resp = web.StreamResponse(
            headers={"Content-Type": "text/event-stream"})
        await resp.prepare(request)
        for i in range(10):
            await resp.write(
                f"id: {i}\ndata: {json.dumps({'i': i})}\n\n".encode())
        # unknown-event filtered out
        await resp.write(b"event: skipme\ndata: {\"i\": 99}\n\n")
        await resp.write_eof()
        return resp

    async def run():
        app = web.Application()
        app.router.add_get("/events", sse_handler)
        base = await aiohttp_servers.start(app)

        clear_sink("sse1")
        prog = (Stream.source("sse", {"endpoint": f"{base}/events",
                                      "events": "message"})
                .sink("memory", {"name": "sse1"}))
        eng = Engine.for_local(prog, "sse-job")
        running = eng.start()
        try:
            await running.join()
        finally:
            await aiohttp_servers.cleanup()

    asyncio.run(run())
    rows = Batch.concat(sink_output("sse1"))
    assert rows.columns["i"].tolist() == list(range(10))


def test_webhook_sink(aiohttp_servers):
    import aiohttp.web as web

    received = []

    async def hook(request):
        received.append(await request.json())
        return web.Response()

    async def run():
        app = web.Application()
        app.router.add_post("/hook", hook)
        base = await aiohttp_servers.start(app)

        prog = (Stream.source("impulse", {"event_rate": 0.0,
                                          "message_count": 20,
                                          "batch_size": 8})
                .map(lambda c: {"counter": c["counter"]}, name="id")
                .sink("webhook", {"endpoint": f"{base}/hook"}))
        eng = Engine.for_local(prog, "hook-job")
        running = eng.start()
        try:
            await running.join()
        finally:
            await aiohttp_servers.cleanup()

    asyncio.run(run())
    assert sorted(r["counter"] for r in received) == list(range(20))


# ---------------------------------------------------------------------------
# two-phase commit edge cases (review regressions)
# ---------------------------------------------------------------------------


def test_commit_epoch_isolation():
    """A Commit for epoch N must not finalize epoch N+1's unsealed work."""
    from arroyo_tpu.connectors.two_phase import TwoPhaseCommitterSink
    from arroyo_tpu.engine.context import Context
    from arroyo_tpu.types import CheckpointBarrier

    committed = []

    class FakeSink(TwoPhaseCommitterSink):
        def __init__(self):
            super().__init__("fake")
            self.n = 0

        async def insert_batch(self, batch, ctx):
            pass

        async def committer_checkpoint(self, epoch, stopping, ctx):
            self.n += 1
            return None, {f"unit-{epoch}": {"epoch": epoch}}

        async def committer_commit(self, epoch, pre_commits, ctx):
            committed.append((epoch, sorted(pre_commits)))

    async def run():
        ctx, _ = Context.new_for_test()
        sink = FakeSink()
        for d in sink.tables():
            ctx.state.register(d)
        await sink.on_start(ctx)
        await sink.pre_checkpoint(CheckpointBarrier(1, 0, 0, False), ctx)
        await sink.pre_checkpoint(CheckpointBarrier(2, 0, 0, False), ctx)
        await sink.handle_commit(1, ctx)
        assert committed == [(1, ["unit-1"])]
        await sink.handle_commit(2, ctx)
        assert committed == [(1, ["unit-1"]), (2, ["unit-2"])]

    asyncio.run(run())


def test_then_stop_checkpoint_commits_before_close(tmp_path):
    """checkpoint(then_stop) + Commit: the sink waits for the commit phase
    before closing, so the final epoch's parts are promoted."""
    out = tmp_path / "fs_stop"
    url = f"file://{tmp_path}/ckpt"
    prog = (Stream.source("impulse", {"event_rate": 100_000.0,
                                      "message_count": 1_000_000,
                                      "batch_size": 256})
            .map(lambda c: {"counter": c["counter"]}, name="id")
            .sink("filesystem", {"path": f"file://{out}", "format": "json"}))

    async def run():
        eng = Engine.for_local(prog, "fsstop-job", checkpoint_url=url)
        running = eng.start()
        await asyncio.sleep(0.05)
        await running.checkpoint(1, then_stop=True)
        assert await running.wait_for_checkpoint(1)
        await running.commit(1)
        await running.join()

    asyncio.run(run())
    finals = list(out.glob("part-*.json"))
    assert finals, "then_stop run left no committed parts"
    assert not list(out.glob(".staging/*")), "staged parts not promoted"


def test_sse_reconnect_resumes_with_last_event_id(aiohttp_servers):
    import aiohttp.web as web

    attempts = []

    async def sse_handler(request):
        attempts.append(request.headers.get("Last-Event-ID"))
        start = int(request.headers.get("Last-Event-ID", -1)) + 1
        resp = web.StreamResponse(
            headers={"Content-Type": "text/event-stream"})
        await resp.prepare(request)
        for i in range(start, 10):
            await resp.write(
                f"id: {i}\ndata: {json.dumps({'i': i})}\n\n".encode())
            if i == 4 and start == 0:
                # abrupt mid-stream drop (no clean EOF) -> client reconnects
                request.transport.close()
                return resp
        await resp.write_eof()
        return resp

    async def run():
        app = web.Application()
        app.router.add_get("/events", sse_handler)
        base = await aiohttp_servers.start(app)

        clear_sink("sse2")
        prog = (Stream.source("sse", {"endpoint": f"{base}/events"})
                .sink("memory", {"name": "sse2"}))
        eng = Engine.for_local(prog, "sse2-job")
        running = eng.start()
        try:
            await running.join()
        finally:
            await aiohttp_servers.cleanup()

    asyncio.run(run())
    rows = Batch.concat(sink_output("sse2"))
    assert sorted(set(rows.columns["i"].tolist())) == list(range(10))
    assert len(attempts) >= 2 and attempts[1] == "4"


def test_rows_with_missing_fields_not_fabricated():
    from arroyo_tpu.formats import rows_to_columns

    cols = rows_to_columns([{"a": 1}, {"b": 2}])
    # numeric column with a missing row -> NaN, never a fabricated 0
    assert np.isnan(cols["a"][1]) and cols["a"][0] == 1.0
    assert np.isnan(cols["b"][0]) and cols["b"][1] == 2.0
    # all-None column stays object of Nones, not all-False booleans
    cols2 = rows_to_columns([{"x": None}, {"x": None}])
    assert cols2["x"].dtype == object and cols2["x"][0] is None


def test_debezium_serialize_does_not_mutate_input():
    fmt = make_format("debezium_json")
    rows = [{"id": 1, "__op": "retract"}]
    first = fmt.serialize(rows)
    second = fmt.serialize(rows)
    assert first == second
    assert json.loads(first[0])["op"] == "d"


# ---------------------------------------------------------------------------
# kinesis (fake client)
# ---------------------------------------------------------------------------


class FakeKinesis:
    """In-memory Kinesis: iterators are '<shard>:<idx>' cursors."""

    def __init__(self, shards=2):
        self.streams = {}
        self.n_shards = shards
        self.put = []

    def seed(self, stream, shard, rows):
        sh = self.streams.setdefault(stream, {})
        log = sh.setdefault(f"shard-{shard:04d}", [])
        for r in rows:
            log.append((f"seq-{shard}-{len(log):06d}",
                        json.dumps(r).encode()))

    def list_shards(self, stream):
        self.streams.setdefault(stream, {})
        for i in range(self.n_shards):
            self.streams[stream].setdefault(f"shard-{i:04d}", [])
        return sorted(self.streams[stream])

    def get_shard_iterator(self, stream, shard_id, after_seq, latest):
        log = self.streams[stream][shard_id]
        if after_seq is not None:
            idx = next(i for i, (s, _) in enumerate(log)
                       if s == after_seq) + 1
        else:
            idx = len(log) if latest else 0
        return f"{shard_id}:{idx}"

    def get_records(self, iterator, limit):
        shard_id, idx = iterator.rsplit(":", 1)
        idx = int(idx)
        stream = next(s for s, shards in self.streams.items()
                      if shard_id in shards)
        log = self.streams[stream][shard_id]
        recs = [{"Data": base64.b64encode(d).decode(), "SequenceNumber": s}
                for s, d in log[idx:idx + limit]]
        return {"Records": recs,
                "NextShardIterator": f"{shard_id}:{idx + len(recs)}"}

    def put_records(self, stream, records):
        self.put.extend(records)


def test_kinesis_source_resume_and_sink(tmp_path, request):
    """Kinesis source reads sharded records, checkpoints per-shard
    sequence numbers, and resumes exactly-once; the sink PutRecords with
    the configured partition key (kinesis/ connector analog)."""
    import base64 as b64

    from arroyo_tpu.connectors.kinesis import (
        register_test_client,
        unregister_test_client,
    )

    fake = FakeKinesis(shards=2)
    for i in range(40):
        fake.seed("evstream", i % 2, [{"i": i}])
    register_test_client("evstream", fake)
    request.addfinalizer(lambda: unregister_test_client("evstream"))
    url = f"file://{tmp_path}/ckpt"
    clear_sink("kin")

    def build():
        return (Stream.source("kinesis", {
                    "stream_name": "evstream", "batch_size": 8,
                    "max_messages": 100})
                .sink("memory", {"name": "kin"}))

    async def run1():
        eng = Engine.for_local(build(), "kin-job", checkpoint_url=url)
        running = eng.start()
        for _ in range(300):
            if sum(len(b) for b in sink_output("kin")) >= 40:
                break
            await asyncio.sleep(0.01)
        await running.checkpoint(1)
        assert await running.wait_for_checkpoint(1)
        await running.stop(StopMode.IMMEDIATE)
        try:
            await running.join()
        except RuntimeError:
            pass

    asyncio.run(run1())
    seen1 = {r for b in sink_output("kin") for r in b.columns["i"].tolist()}
    assert seen1 == set(range(40))
    clear_sink("kin")

    # new records arrive while the job is down; restore must not re-read
    for i in range(40, 60):
        fake.seed("evstream", i % 2, [{"i": i}])

    async def run2():
        eng = Engine.for_local(build(), "kin-job", checkpoint_url=url,
                               restore_epoch=1)
        running = eng.start()
        for _ in range(300):
            got = {r for b in sink_output("kin")
                   for r in b.columns["i"].tolist()}
            if got >= set(range(40, 60)):
                break
            await asyncio.sleep(0.01)
        await running.stop(StopMode.IMMEDIATE)
        try:
            await running.join()
        except RuntimeError:
            pass

    asyncio.run(run2())
    seen2 = {r for b in sink_output("kin") for r in b.columns["i"].tolist()}
    assert seen2 == set(range(40, 60))  # exactly the new records

    # sink side
    clear_sink("kin")
    src = Batch(np.arange(5, dtype=np.int64),
                {"k": np.array([1, 2, 1, 2, 1]),
                 "v": np.arange(5, dtype=np.int64)})
    prog = (Stream.source("memory", {"batches": [src]})
            .sink("kinesis", {"stream_name": "evstream",
                              "partition_key_field": "k"}))
    LocalRunner(prog).run()
    assert len(fake.put) == 5
    assert {r["PartitionKey"] for r in fake.put} == {"1", "2"}
    rows = [json.loads(b64.b64decode(r["Data"])) for r in fake.put]
    assert sorted(r["v"] for r in rows) == [0, 1, 2, 3, 4]


# ---------------------------------------------------------------------------
# fluvio (in-memory log)
# ---------------------------------------------------------------------------


def test_fluvio_source_to_sink_roundtrip():
    """events flow fluvio topic -> engine -> fluvio sink topic; the sink is
    at-least-once (produced eagerly, flushed at barriers)."""
    InMemoryKafkaBroker.reset("fl1")
    broker = InMemoryKafkaBroker.get("fl1")
    broker.create_topic("in", partitions=3)
    for i in range(90):
        broker.produce("in", json.dumps({"i": i}).encode(), partition=i % 3)

    prog = (Stream.source("fluvio", {"endpoint": "memory://fl1",
                                     "topic": "in", "max_messages": 90})
            .map(lambda c: {"i": c["i"] * 2}, name="dbl")
            .sink("fluvio", {"endpoint": "memory://fl1", "topic": "out"}))
    LocalRunner(prog).run()

    out = [json.loads(r.value)["i"]
           for r in broker.fetch("out", 0, 0, 10_000, read_committed=False)]
    assert sorted(out) == [2 * i for i in range(90)]


def test_fluvio_source_absolute_offset_resume(tmp_path):
    """checkpoint stores partition -> next offset; a restore resumes
    absolutely with no re-reads (source.rs:129-156, 214-223)."""
    InMemoryKafkaBroker.reset("fl2")
    broker = InMemoryKafkaBroker.get("fl2")
    broker.create_topic("ev", partitions=2)
    for i in range(40):
        broker.produce("ev", json.dumps({"i": i}).encode(), partition=i % 2)

    url = f"file://{tmp_path}/ckpt"
    clear_sink("fl-out")

    def build():
        return (Stream.source("fluvio", {"endpoint": "memory://fl2",
                                         "topic": "ev", "batch_size": 8})
                .sink("memory", {"name": "fl-out"}))

    async def run1():
        eng = Engine.for_local(build(), "fluvio-job", checkpoint_url=url)
        running = eng.start()
        for _ in range(200):
            if sum(len(b) for b in sink_output("fl-out")) >= 40:
                break
            await asyncio.sleep(0.01)
        await running.checkpoint(1)
        assert await running.wait_for_checkpoint(1)
        await running.stop(StopMode.IMMEDIATE)
        try:
            await running.join()
        except RuntimeError:
            pass

    asyncio.run(run1())
    seen1 = {r for b in sink_output("fl-out") for r in b.columns["i"].tolist()}
    assert seen1 == set(range(40))
    clear_sink("fl-out")

    for i in range(40, 80):
        broker.produce("ev", json.dumps({"i": i}).encode(), partition=i % 2)

    async def run2():
        eng = Engine.for_local(build(), "fluvio-job", checkpoint_url=url,
                               restore_epoch=1)
        running = eng.start()
        for _ in range(300):
            got = {r for b in sink_output("fl-out")
                   for r in b.columns["i"].tolist()}
            if got >= set(range(40, 80)):
                break
            await asyncio.sleep(0.01)
        await running.stop(StopMode.IMMEDIATE)
        try:
            await running.join()
        except RuntimeError:
            pass

    asyncio.run(run2())
    seen2 = {r for b in sink_output("fl-out") for r in b.columns["i"].tolist()}
    assert seen2 == set(range(40, 80))  # exactly the new records, no re-reads


def test_fluvio_latest_offset_and_registry():
    from arroyo_tpu.connectors.registry import get_connector

    meta = get_connector("fluvio")
    assert meta.supports_source and meta.supports_sink

    InMemoryKafkaBroker.reset("fl3")
    broker = InMemoryKafkaBroker.get("fl3")
    broker.create_topic("ev", partitions=1)
    for i in range(10):
        broker.produce("ev", json.dumps({"i": i}).encode(), partition=0)

    clear_sink("fl3-out")
    prog = (Stream.source("fluvio", {"endpoint": "memory://fl3", "topic": "ev",
                                     "offset": "latest", "max_messages": 5})
            .sink("memory", {"name": "fl3-out"}))

    # the source computes its 'latest' position before its first fetch, so
    # the first fetch call is the deterministic signal that producing more
    # records can no longer race the tail snapshot
    fetched = asyncio.Event()
    real_fetch = broker.fetch

    def observed_fetch(*a, **k):
        fetched.set()
        return real_fetch(*a, **k)

    broker.fetch = observed_fetch

    async def run():
        eng = Engine.for_local(prog, "fluvio-latest")
        running = eng.start()
        await asyncio.wait_for(fetched.wait(), timeout=10)
        for i in range(10, 15):
            broker.produce("ev", json.dumps({"i": i}).encode(), partition=0)
        for _ in range(300):
            if sum(len(b) for b in sink_output("fl3-out")) >= 5:
                break
            await asyncio.sleep(0.01)
        try:
            await running.join()
        except RuntimeError:
            pass

    asyncio.run(run())
    seen = {r for b in sink_output("fl3-out") for r in b.columns["i"].tolist()}
    assert seen == set(range(10, 15))  # old records skipped by 'latest'


def test_kinesis_reshard_child_discovery(request):
    """When a shard closes (reshard), its drained parent is never re-opened
    and newly-listed child shards are picked up by the stable hash
    assignment — no loss, no duplicates."""
    from arroyo_tpu.connectors.kinesis import (
        register_test_client,
        unregister_test_client,
    )

    class ReshardingKinesis(FakeKinesis):
        def __init__(self):
            super().__init__(shards=1)
            self.closed = False
            self.iter_opens = []

        def list_shards(self, stream):
            base = super().list_shards(stream)
            return base if not self.closed else sorted(
                set(base) | {"shard-child"})

        def get_shard_iterator(self, stream, shard_id, after_seq, latest):
            self.iter_opens.append(shard_id)
            if shard_id == "shard-child":
                self.streams[stream].setdefault("shard-child", [])
            return super().get_shard_iterator(stream, shard_id, after_seq,
                                              latest)

        def get_records(self, iterator, limit):
            out = super().get_records(iterator, limit)
            shard_id = iterator.rsplit(":", 1)[0]
            if self.closed and shard_id == "shard-0000" and not out["Records"]:
                out["NextShardIterator"] = None  # parent fully drained
            return out

    fake = ReshardingKinesis()
    fake.seed("rstream", 0, [{"i": i} for i in range(10)])
    register_test_client("rstream", fake)
    request.addfinalizer(lambda: unregister_test_client("rstream"))
    clear_sink("rkin")

    async def run():
        prog = (Stream.source("kinesis", {"stream_name": "rstream",
                                          "batch_size": 4,
                                          "max_messages": 15})
                .sink("memory", {"name": "rkin"}))
        eng = Engine.for_local(prog, "kinesis-reshard")
        running = eng.start()
        # wait for the parent's 10 rows, then trigger the reshard
        for _ in range(300):
            if sum(len(b) for b in sink_output("rkin")) >= 10:
                break
            await asyncio.sleep(0.01)
        fake.closed = True
        # child rows appear after the reshard
        fake.streams["rstream"].setdefault("shard-child", [])
        log = fake.streams["rstream"]["shard-child"]
        for i in range(10, 15):
            log.append((f"seq-c-{i}", json.dumps({"i": i}).encode()))
        try:
            await running.join()
        except RuntimeError:
            pass

    asyncio.run(run())
    seen = sorted(r for b in sink_output("rkin")
                  for r in b.columns["i"].tolist())
    assert seen == list(range(15))  # parent + child, exactly once
    # the drained parent was opened exactly once: never re-opened from the
    # retention-window listing
    assert fake.iter_opens.count("shard-0000") == 1


# ---------------------------------------------------------------------------
# avro
# ---------------------------------------------------------------------------


def test_avro_roundtrip_and_kafka():
    """Avro binary serde (the reference leaves this as TODO in formats.rs)
    + kafka e2e with confluent framing."""
    from arroyo_tpu.formats import AvroFormat, avro_schema_for_rows

    rows = [{"i": 1, "s": "ab", "f": 2.5, "b": True, "n": None},
            {"i": -7, "s": "", "f": -0.125, "b": False, "n": 3}]
    schema = avro_schema_for_rows(rows)
    f = AvroFormat(schema=schema)
    assert AvroFormat(schema=schema).deserialize(f.serialize(rows)) == rows

    # confluent framing: magic 0 + schema id, as registry producers emit
    fc = AvroFormat(schema=schema, confluent_schema_registry=True,
                    schema_id=42)
    [p, _] = fc.serialize(rows)
    assert p[:5] == b"\x00\x00\x00\x00\x2a"

    # kafka -> engine -> memory with format=avro
    InMemoryKafkaBroker.reset("av1")
    broker = InMemoryKafkaBroker.get("av1")
    broker.create_topic("ev", partitions=1)
    src_schema = avro_schema_for_rows([{"i": 0}])
    enc = AvroFormat(schema=src_schema)
    for i in range(50):
        [payload] = enc.serialize([{"i": i}])
        broker.produce("ev", payload, partition=0)

    clear_sink("av-out")
    prog = (Stream.source("kafka", {"bootstrap_servers": "memory://av1",
                                    "topic": "ev", "format": "avro",
                                    "format_options": {"schema": src_schema},
                                    "max_messages": 50})
            .sink("memory", {"name": "av-out"}))
    LocalRunner(prog).run()
    got = sorted(r for b in sink_output("av-out")
                 for r in b.columns["i"].tolist())
    assert got == list(range(50))


def test_avro_rejects_unsupported_schema_shapes():
    """Only ["null", T] unions are wire-compatible with this encoder; a
    plain field type or [T, "null"] ordering must fail loudly, not
    mis-frame bytes (reviewer-reproduced corruption)."""
    from arroyo_tpu.formats import AvroFormat

    plain = {"type": "record", "name": "r",
             "fields": [{"name": "i", "type": "long"}]}
    with pytest.raises(ValueError, match="null"):
        AvroFormat(schema=plain).serialize([{"i": 5}])
    flipped = {"type": "record", "name": "r",
               "fields": [{"name": "i", "type": ["long", "null"]}]}
    with pytest.raises(ValueError, match="null"):
        AvroFormat(schema=flipped).deserialize([b"\x02\x0a"])
    exotic = {"type": "record", "name": "r",
              "fields": [{"name": "m", "type": ["null", {"type": "map",
                                                         "values": "long"}]}]}
    with pytest.raises(ValueError, match="unsupported"):
        AvroFormat(schema=exotic).serialize([{"m": {}}])

    # serialize without a schema stays stateless: the instance is not
    # mutated by inference
    f = AvroFormat()
    f.serialize([{"a": 1}])
    assert f.schema is None


def test_avro_logical_types_and_framing_guard():
    """logicalType fields use their UNDERLYING type's wire encoding; a
    confluent-mode decoder only strips a header that is present."""
    from arroyo_tpu.formats import AvroFormat

    schema = {"type": "record", "name": "r", "fields": [
        {"name": "u", "type": ["null", {"type": "string",
                                        "logicalType": "uuid"}]},
        {"name": "ts", "type": ["null", {"type": "long",
                                         "logicalType": "timestamp-micros"}]},
    ]}
    rows = [{"u": "ab-cd", "ts": 123456}]
    f = AvroFormat(schema=schema)
    assert AvroFormat(schema=schema).deserialize(f.serialize(rows)) == rows

    # unframed payload with confluent=True decodes intact (guarded strip)
    fc = AvroFormat(schema=schema, confluent_schema_registry=True)
    plain = f.serialize(rows)
    if plain[0][0] != 0:  # only meaningful when no accidental magic byte
        assert fc.deserialize(plain) == rows


def test_kinesis_shardless_subtask_does_not_stall_watermark(request):
    """parallelism > shards: the shardless subtask declares itself IDLE so
    windows still fire from the active subtask's data (reviewer-found
    stall; the reference broadcasts Watermark::Idle the same way)."""
    from arroyo_tpu.connectors.kinesis import (
        register_test_client,
        unregister_test_client,
    )
    from arroyo_tpu.graph.logical import AggKind, AggSpec

    fake = FakeKinesis(shards=1)
    # timestamps spread over 3s so a 1s tumbling window closes in-stream
    for i in range(30):
        fake.seed("idlestream", 0, [{"i": i, "ts": i * 100_000}])
    register_test_client("idlestream", fake)
    request.addfinalizer(lambda: unregister_test_client("idlestream"))
    clear_sink("idle-out")

    prog = (Stream.source("kinesis", {"stream_name": "idlestream",
                                      "batch_size": 8, "max_messages": 30},
                          parallelism=2)
            .udf(lambda c: {**c, "__timestamp": c["ts"]}, name="evt")
            .watermark(max_lateness_micros=0)
            .key_by("i")
            .tumbling_aggregate(1_000_000,
                                [AggSpec(AggKind.COUNT, None, "cnt")],
                                parallelism=1)
            .sink("memory", {"name": "idle-out"}))
    LocalRunner(prog).run()
    total = sum(int(c) for b in sink_output("idle-out")
                for c in b.columns["cnt"].tolist())
    assert total == 30  # every record aggregated; no watermark deadlock


# ---------------------------------------------------------------------------
# nexmark generator resume determinism
# ---------------------------------------------------------------------------


def test_nexmark_generator_resume_is_identical_stream():
    """Exactly-once requires the resumed generator to produce the
    IDENTICAL stream an uninterrupted run would.  RNG draws are blocked
    per call site within each generated batch, so the source's restore
    replay-burn regenerates the delivered prefix with the SAME batch
    size — landing every stream in the original position (a bare
    events_so_far fast-forward regenerated DIFFERENT events; caught by
    the raw-argmax restore fuzz)."""
    from arroyo_tpu.connectors.nexmark import NexmarkConfig, NexmarkGenerator

    cfg = NexmarkConfig(event_rate=10000.0, num_events=30000,
                        batch_size=2048)

    def make():
        g = NexmarkGenerator(cfg, 1_700_000_000_000_000, 0, 30000, 1,
                             seed=0)
        g.set_rate(cfg.event_rate, 1)
        return g

    def drain(g, size):
        cols = {}
        while g.has_next:
            b, _ = g.next_batch(size)
            for c, v in b.columns.items():
                cols.setdefault(c, []).append(np.asarray(v))
        return {c: np.concatenate(v) for c, v in cols.items()}

    full = drain(make(), 2048)

    # resume mid-stream: burn 3 delivery-sized batches, then continue —
    # the tail must be byte-identical to the uninterrupted stream
    g2 = make()
    for _ in range(3):
        g2.next_batch(2048)
    assert g2.events_so_far == 6144
    rest = drain(g2, 2048)
    for c in full:
        np.testing.assert_array_equal(full[c][6144:], rest[c], err_msg=c)


def test_nexmark_source_persists_rng_snapshot_4tuple():
    """Regression lock for the round-5 crash: the source's run loop must
    unpack the prefetch 4-tuple (batch, nums, count, rng_snapshot) and
    persist ALL FOUR in state — making the O(1) RNG-snapshot restore path
    live — and a source resumed from that snapshot must produce the
    identical tail an uninterrupted run would."""
    from arroyo_tpu.connectors.nexmark import (NexmarkConfig,
                                               NexmarkGenerator,
                                               NexmarkSource)
    from arroyo_tpu.engine.context import Context
    from arroyo_tpu.types import MessageKind

    base = 1_700_000_000_000_000
    cfg = {"event_rate": 1e7, "num_events": 8192, "batch_size": 1024,
           "rate_limited": False, "base_time_micros": base}

    async def run_source(preset_state=None):
        src = NexmarkSource(cfg)
        ctx, q = Context.new_for_test()
        for d in src.tables():
            ctx.state.register(d)
        state = ctx.state.get_global_keyed_state("s")
        if preset_state is not None:
            state.insert(0, preset_state)
        await src.run(ctx)
        batches = []
        while not q.empty():
            m = q.get_nowait()
            if m.kind == MessageKind.RECORD:
                batches.append(m.batch)
        return state.get(0), batches

    loop = asyncio.new_event_loop()
    try:
        saved, full = loop.run_until_complete(run_source())
        # the checkpointed tuple carries the RNG snapshot (4th element)
        assert len(saved) == 4, saved[:3]
        base_time, split, count, rng_snap = saved
        assert count == 8192
        assert isinstance(rng_snap, dict) and "__base" in rng_snap

        # mid-stream snapshot, taken exactly how the source takes it:
        # count and RNG states captured together at generation time
        gen = NexmarkGenerator(NexmarkConfig(**cfg), base, split[0],
                               split[1], split[2], seed=0)
        gen.set_rate(cfg["event_rate"], 1)
        for _ in range(3):
            gen.next_batch(1024)
        preset = (base, split, gen.events_so_far,
                  gen.snapshot_rng_state())
        _, resumed = loop.run_until_complete(run_source(preset))
    finally:
        loop.close()

    def concat(batches):
        cols = {}
        ts = np.concatenate([b.timestamp for b in batches])
        for b in batches:
            for c, v in b.columns.items():
                cols.setdefault(c, []).append(np.asarray(v))
        return ts, {c: np.concatenate(v) for c, v in cols.items()}

    full_ts, full_cols = concat(full)
    res_ts, res_cols = concat(resumed)
    np.testing.assert_array_equal(full_ts[3072:], res_ts)
    assert set(full_cols) == set(res_cols)
    for c in full_cols:
        np.testing.assert_array_equal(full_cols[c][3072:], res_cols[c],
                                      err_msg=c)
