"""End-to-end latency observatory (obs/latency.py): deterministic
1-in-N sampling, stamp survival across chain / coalesce / wire / window
fire / join / checkpoint-restore (with the sanitizer armed by conftest,
so any schema-signature flip fails loudly), critical-path attribution,
SLO burn math, controller rollup + REST round-trip, and the off-path
discipline (disarmed records nothing)."""

import asyncio
import time

import numpy as np
import pytest

from arroyo_tpu import AggKind, AggSpec, Batch, Stream, TumblingWindow
from arroyo_tpu.config import reset_config
from arroyo_tpu.connectors.memory import clear_sink, sink_output
from arroyo_tpu.engine.engine import LocalRunner
from arroyo_tpu.obs import latency
from arroyo_tpu.types import TaskInfo, hash_columns

SEC = 1_000_000


@pytest.fixture(autouse=True)
def _observatory_guard():
    """Torn down LAST (autouse set up first): after monkeypatch undoes
    env edits, re-read config so no latency/SLO setting leaks into the
    rest of the suite."""
    latency.disarm()
    reset_config()
    yield
    latency.disarm()
    reset_config()


@pytest.fixture
def sampled(monkeypatch):
    """Arm sampling at 1-in-1 (every batch stamps) the way a real run
    does: env -> config -> engine ensure_armed picks it up."""
    monkeypatch.setenv("ARROYO_LATENCY_SAMPLE_N", "1")
    reset_config()
    lat = latency.arm("test-job", 1)
    yield lat
    latency.disarm()


def _events(rng, n=400, n_keys=8, span=4 * SEC):
    ts = np.sort(rng.integers(0, span, n)).astype(np.int64)
    return Batch(ts, {"k": rng.integers(0, n_keys, n).astype(np.int64),
                      "v": rng.integers(1, 100, n).astype(np.int64)})


def run_pipeline(batches, build, sink="out"):
    clear_sink(sink)
    prog = build(Stream.source("memory", {"batches": batches})
                 .watermark(max_lateness_micros=0))
    LocalRunner(prog).run()
    return sink_output(sink)


# -- deterministic sampling ---------------------------------------------------


def test_source_stamp_deterministic_1_in_n():
    obs = latency.LatencyObservatory("j", sample_n=10)
    # 25 batches x 4 rows = 100 rows -> exactly 10 crossings of a
    # multiple of 10, at positions independent of wall clock
    fired = [obs.source_stamp("s", 4) is not None for _ in range(25)]
    assert sum(fired) == 10
    obs2 = latency.LatencyObservatory("j", sample_n=10)
    assert [obs2.source_stamp("s", 4) is not None
            for _ in range(25)] == fired
    # a single batch spanning several multiples still yields one stamp
    obs3 = latency.LatencyObservatory("j", sample_n=10)
    assert obs3.source_stamp("s", 35) is not None
    assert obs3.snapshot()["records_sampled"] == 1
    # empty batches never sample
    assert obs3.source_stamp("s", 0) is None


def test_maybe_stamp_never_overwrites(sampled):
    b = Batch(np.array([1], dtype=np.int64),
              {"v": np.array([7], dtype=np.int64)})
    b.lat_stamp = 12345
    latency.maybe_stamp("src", b)
    assert b.lat_stamp == 12345  # caller's stamp (replays/tests) wins
    b2 = Batch(np.array([1], dtype=np.int64),
               {"v": np.array([7], dtype=np.int64)})
    latency.maybe_stamp("src", b2)
    assert b2.lat_stamp is not None  # sample_n=1: every batch stamps


# -- side-channel schema stability -------------------------------------------


def test_stamp_is_schema_invisible(rng):
    """The stamp is a batch annotation, not a column: the coalescer
    signature (what arroyosan's schema-stability check keys on) must be
    identical with and without it."""
    from arroyo_tpu.engine.coalesce import _signature

    mk = lambda: Batch(np.array([1, 2], dtype=np.int64),
                       {"k": np.array([3, 4], dtype=np.int64)})
    plain, stamped = mk(), mk()
    stamped.lat_stamp = 777
    assert _signature(plain) == _signature(stamped)
    assert latency.STAMP_COLUMN not in stamped.columns


def test_stamp_transform_and_concat_semantics(rng):
    keys = rng.integers(0, 5, 16).astype(np.int64)
    b = Batch(np.arange(16, dtype=np.int64), {"k": keys},
              hash_columns([keys]), ("k",), lat_stamp=500)
    assert b.select(np.arange(4)).lat_stamp == 500
    a = Batch(np.array([1], dtype=np.int64),
              {"k": np.array([1], dtype=np.int64)}, lat_stamp=900)
    c = Batch(np.array([2], dtype=np.int64),
              {"k": np.array([2], dtype=np.int64)})  # unstamped
    merged = Batch.concat([a, c,
                           Batch(np.array([3], dtype=np.int64),
                                 {"k": np.array([3], dtype=np.int64)},
                                 lat_stamp=200)])
    # coalescing keeps the OLDEST stamp: linger is charged, never hidden
    assert merged.lat_stamp == 200
    assert Batch.concat([c]).lat_stamp is None


def test_device_shuffle_threads_stamp(rng, monkeypatch):
    monkeypatch.setenv("ARROYO_SHUFFLE_DEVICE", "on")
    from arroyo_tpu.parallel import shuffle as shf

    keys = rng.integers(0, 300, 2000).astype(np.int64)
    kh = hash_columns([keys])
    b = Batch(np.sort(rng.integers(0, SEC, 2000)).astype(np.int64),
              {"k": keys, "v": rng.standard_normal(2000)}, kh, ("k",),
              lat_stamp=4242)
    parts = shf.DeviceShuffle(4, op_id="t").route(b)
    assert parts is not None and len(parts) > 0
    for _dest, sub in parts:
        assert sub.lat_stamp == 4242


def test_wire_frame_stamp_roundtrip():
    """The stamp rides as a frame-flag + 8 bytes OUTSIDE the Arrow
    payload — framing must round-trip it and hand back the unflagged
    kind, and stampless frames must be byte-identical to before."""
    from arroyo_tpu.network import data_plane as dp

    class _W:
        def __init__(self):
            self.buf = bytearray()

        def write(self, b):
            self.buf += bytes(b)

    async def roundtrip(stamp):
        w = _W()
        dp._write_frame(w, ("src", 0, "dst", 1), dp.KIND_DATA,
                        b"payload", stamp)
        r = asyncio.StreamReader()
        r.feed_data(bytes(w.buf))
        r.feed_eof()
        return await dp._read_frame(r), len(w.buf)

    loop = asyncio.new_event_loop()
    try:
        (frame, n_stamped) = loop.run_until_complete(roundtrip(123456789))
        quad, kind, payload, stamp = frame
        assert quad == ("src", 0, "dst", 1)
        assert kind == dp.KIND_DATA  # flag stripped
        assert payload == b"payload" and stamp == 123456789
        (frame, n_plain) = loop.run_until_complete(roundtrip(None))
        assert frame[1] == dp.KIND_DATA and frame[3] is None
        assert n_stamped == n_plain + 8  # stamp is exactly 8 extra bytes
    finally:
        loop.close()


def test_shardcheck_models_stamp_as_transportable():
    from arroyo_tpu.analysis import shardcheck

    # the constants are pinned in sync across the two layers
    assert shardcheck._LAT_STAMP_COLUMN == latency.STAMP_COLUMN
    # even a mis-modeled stamp kind can never pin an edge to the
    # sticky host route
    assert shardcheck._has_string({latency.STAMP_COLUMN: "s"}) is None
    assert shardcheck._has_string({"name": "s"}) == "name"


# -- stamp survival: end-to-end pipelines (sanitizer armed via conftest) -----


def test_e2e_chain_coalesce_sink_latency(rng, sampled):
    batches = [_events(rng, n=64) for _ in range(6)]
    outs = run_pipeline(
        batches,
        lambda s: s.map(lambda c: {"k": c["k"], "v2": c["v"] * 2}, name="m1")
                   .map(lambda c: {"k": c["k"], "v2": c["v2"]}, name="m2")
                   .sink("memory", {"name": "out"}))
    assert outs and any(b.lat_stamp is not None for b in outs)
    q = sampled.sink_quantiles()
    assert q, "sink recorded no latency samples"
    (stats,) = q.values()
    assert stats["count"] >= 1 and stats["p99_ms"] >= 0.0
    snap = sampled.snapshot()
    assert snap["records_sampled"] >= 1
    assert snap["records_seen"] >= 6 * 64


def test_e2e_unchained_stamp_survives(rng, sampled, monkeypatch):
    """ARROYO_CHAIN=0 reproduces the pre-chaining per-operator queue
    topology — the stamp must survive the queue hops too."""
    monkeypatch.setenv("ARROYO_CHAIN", "0")
    reset_config()
    outs = run_pipeline(
        [_events(rng, n=64) for _ in range(4)],
        lambda s: s.map(lambda c: {"k": c["k"], "v": c["v"]}, name="m")
                   .sink("memory", {"name": "out"}))
    assert outs and any(b.lat_stamp is not None for b in outs)
    assert sampled.sink_quantiles()


def test_e2e_window_fire_inherits_stamp(rng, sampled):
    outs = run_pipeline(
        [_events(rng, n=200) for _ in range(3)],
        lambda s: s.key_by("k")
                   .tumbling_aggregate(SEC, [AggSpec(AggKind.SUM, "v", "s"),
                                             AggSpec(AggKind.COUNT, None,
                                                     "cnt")])
                   .sink("memory", {"name": "out"}))
    assert outs and any(b.lat_stamp is not None for b in outs)
    q = sampled.sink_quantiles()
    assert q and next(iter(q.values()))["count"] >= 1
    # the fired pane charged its hold time to the watermark_hold stage
    assert sampled._stage_counts.get("watermark_hold", 0) >= 1
    assert sampled.critical_path()["stages"]["watermark_hold"] >= 0.0


def test_e2e_join_inherits_stamp(rng, sampled):
    t = lambda s: int(s * SEC)
    l = Batch(np.array([t(0.1), t(0.2)], dtype=np.int64),
              {"pid": np.array([1, 2], dtype=np.int64),
               "lv": np.array([10, 20], dtype=np.int64)})
    r = Batch(np.array([t(0.3), t(0.4)], dtype=np.int64),
              {"pid": np.array([1, 2], dtype=np.int64),
               "rv": np.array([100, 200], dtype=np.int64)})
    clear_sink("out")
    left = (Stream.source("memory", {"batches": [l]})
            .watermark(max_lateness_micros=0).key_by("pid"))
    right = (Stream.source("memory", {"batches": [r]},
                           program=left.program)
             .watermark(max_lateness_micros=0).key_by("pid"))
    prog = (left.window_join(right, TumblingWindow(SEC))
            .sink("memory", {"name": "out"}))
    LocalRunner(prog).run()
    outs = sink_output("out")
    assert outs and any(b.lat_stamp is not None for b in outs)
    assert sampled.sink_quantiles()


def test_pane_stamp_survives_checkpoint_restore_rescale(sampled):
    """A sampled record held in pane state at barrier time is still
    measured after recovery: the pending (max-stamp) rides the canonical
    snapshot as ``__lat_stamp`` and is popped back out BEFORE the
    rescale re-partition filter ever sees it."""
    from arroyo_tpu.engine.operators_window import BinAggOperator

    class _Store:
        def __init__(self):
            self.tables = {}

        def register_device(self, desc, table):
            self.tables[desc.name] = table
            return None

    class _Ctx:
        def __init__(self, idx, par):
            self.task_info = TaskInfo("j", "w", "w", idx, par)
            self.state = _Store()

    aggs = (AggSpec(AggKind.SUM, "v", "s"),)
    loop = asyncio.new_event_loop()
    try:
        op = BinAggOperator("w", SEC, SEC, aggs)
        ctx = _Ctx(0, 1)
        loop.run_until_complete(op.on_start(ctx))
        table = ctx.state.tables["a"]
        # no pending sample -> canonical snapshot format is unchanged
        assert "__lat_stamp" not in table.snapshot()
        op._lat_pending = (987654321, time.monotonic())
        arrays = table.snapshot()
        assert int(arrays["__lat_stamp"][0]) == 987654321

        # restore into a RESCALED successor (parallelism 2): the stamp
        # comes back and filter_canonical_snapshot still sees a clean
        # canonical dict
        op2 = BinAggOperator("w", SEC, SEC, aggs)
        ctx2 = _Ctx(0, 2)
        loop.run_until_complete(op2.on_start(ctx2))
        ctx2.state.tables["a"].restore(dict(arrays))
        assert op2._lat_pending is not None
        assert op2._lat_pending[0] == 987654321
    finally:
        loop.close()


# -- watermark lineage / critical path ---------------------------------------


def test_lineage_attribution_seeded_slow_stage(sampled):
    """Seed a slow stage and check the decomposition names it dominant
    with the right share."""
    sampled.note_stage("watermark_hold", 3.0)
    sampled.note_stage("barrier_align", 1.0)
    cp = sampled.critical_path()
    assert cp["dominant"] == "watermark_hold"
    assert cp["dominant_share"] == pytest.approx(0.75)
    assert cp["total_secs"] == pytest.approx(4.0)
    sampled.note_edge_watermark("agg", latency.now_micros() - 2_000_000)
    wm = sampled.snapshot()["watermarks"]
    assert wm["agg"]["age_ms"] >= 2000.0


def test_summary_ride_alongs_shape(sampled):
    ti = TaskInfo("test-job", "sink-1", "sink", 0, 1)
    sampled.observe_sink(ti, latency.now_micros() - 5000)
    sampled.note_edge_watermark("agg", latency.now_micros())
    sampled.note_stage("watermark_hold", 0.5)
    out = latency.summary_ride_alongs("test-job")
    assert out["sink-1"]["e2e_latency.count"] == 1.0
    assert out["sink-1"]["e2e_latency.p99_ms"] >= 5.0
    assert "wm_age_ms" in out["agg"]
    w = out["__worker__"]
    assert w["critical_path.watermark_hold"] == pytest.approx(0.5)
    assert w["latency_sample_n"] == 1.0
    # a different job's heartbeat gets nothing from this observatory
    assert latency.summary_ride_alongs("other-job") == {}


# -- SLO engine ---------------------------------------------------------------


def test_burn_rate_pure_math():
    assert latency.burn_rate([], 100.0, 60.0) == 0.0
    samples = [(10.0, True), (50.0, True), (90.0, False), (95.0, True)]
    # window [40, 100]: True, False, True -> 2/3
    assert latency.burn_rate(samples, 100.0, 60.0) == pytest.approx(2 / 3)
    # tiny window sees only the newest sample
    assert latency.burn_rate(samples, 100.0, 5.0) == 1.0
    # everything aged out reads healthy, not violating
    assert latency.burn_rate(samples, 1000.0, 60.0) == 0.0


def test_slo_evaluator_verdicts():
    ev = latency.SloEvaluator("j", latency.Slo(p99_ms=100.0,
                                               staleness_ms=500.0,
                                               burn_window_secs=60.0))
    # no measurements yet: absence of evidence never violates
    v = ev.evaluate(None, None, now=1.0)
    assert not v["violating"] and ev.violations_total == 0
    v = ev.evaluate(150.0, 100.0, now=2.0)
    assert v["violating"] and list(v["violated_dims"]) == ["p99"]
    assert ev.violations_total == 1
    v = ev.evaluate(50.0, 900.0, now=3.0)
    assert v["violating"] and list(v["violated_dims"]) == ["staleness"]
    v = ev.evaluate(50.0, 100.0, now=4.0)
    assert not v["violating"]
    assert v["burn_rate"] == pytest.approx(0.5)  # 2 of 4 in window
    assert ev.current_burn_rate == pytest.approx(0.5)
    j = ev.to_json()
    assert j["configured"] and j["violations_total"] == 2
    assert len(j["recent_violations"]) == 2
    # unconfigured SLO never violates no matter the measurement
    idle = latency.SloEvaluator("j", latency.Slo())
    assert not idle.evaluate(1e9, 1e9, now=1.0)["violating"]
    assert not latency.Slo().configured()


def test_slo_from_config(monkeypatch):
    monkeypatch.setenv("ARROYO_SLO_P99_MS", "250")
    monkeypatch.setenv("ARROYO_SLO_BURN_WINDOW_SECS", "0")
    reset_config()
    slo = latency.Slo.from_config()
    assert slo.p99_ms == 250.0 and slo.configured()
    assert slo.burn_window_secs == 60.0  # 0 falls back to the default


def test_autoscaler_slo_pressure():
    """The burn rate pressures only operators that report sink latency
    (that's where the debt is observable), and blocks scale-down."""
    from arroyo_tpu.autoscale.policy import (BacklogDrainPolicy, EvalInput,
                                             PolicyConfig)

    pol = BacklogDrainPolicy(PolicyConfig())
    mk = lambda burn: EvalInput(
        now=10.0,
        rollups=[{"operator_id": "sink-1", "e2e_latency.p99_ms": 500.0},
                 {"operator_id": "map-1"}],
        parallelism={"sink-1": 1, "map-1": 1},
        upstream={"sink-1": ["map-1"], "map-1": []},
        slo_burn=burn)
    sig = pol.signals(mk(1.0))
    assert sig["sink-1"]["pressure"] == 1.0
    assert sig["sink-1"]["calm_pressure"] == 1.0  # blocks scale-down
    assert sig["map-1"]["pressure"] == 0.0  # burn lands on sinks only
    assert pol.signals(mk(0.0))["sink-1"]["pressure"] == 0.0


# -- rollup + REST round-trip -------------------------------------------------


def test_rollup_latency_key_semantics():
    from arroyo_tpu.controller.controller import ControllerServer

    agg = {}
    ControllerServer._rollup_op(agg, {
        "e2e_latency.p99_ms": 120.0, "e2e_latency.p50_ms": 40.0,
        "e2e_latency.count": 5.0, "wm_age_ms": 30.0,
        "critical_path.fire": 1.0, "device_bytes.panes": 100.0,
        "latency_sample_n": 64.0}, None, 0.0)
    ControllerServer._rollup_op(agg, {
        "e2e_latency.p99_ms": 80.0, "e2e_latency.p50_ms": 60.0,
        "e2e_latency.count": 3.0, "wm_age_ms": 50.0,
        "critical_path.fire": 2.0, "device_bytes.panes": 50.0,
        "latency_sample_n": 64.0}, None, 0.0)
    # quantiles/ages: worst worker (summing would fabricate latency)
    assert agg["e2e_latency.p99_ms"] == 120.0
    assert agg["e2e_latency.p50_ms"] == 60.0
    assert agg["wm_age_ms"] == 50.0
    assert agg["latency_sample_n"] == 64.0
    # stage seconds / byte tables / sample counts: sum across workers
    assert agg["e2e_latency.count"] == 8.0
    assert agg["critical_path.fire"] == 3.0
    assert agg["device_bytes.panes"] == 150.0


def test_latency_shape():
    from arroyo_tpu.controller.controller import ControllerServer

    rows = [
        {"operator_id": "__worker__", "critical_path.fire": 2.0,
         "critical_path.compute": 6.0, "device_bytes.panes": 512.0,
         "latency_sample_n": 64.0},
        {"operator_id": "sink-1", "e2e_latency.p50_ms": 5.0,
         "e2e_latency.p99_ms": 42.0, "e2e_latency.last_ms": 6.0,
         "e2e_latency.count": 9.0},
        {"operator_id": "agg-1", "wm_age_ms": 17.0},
    ]
    shape = ControllerServer.latency_shape(rows)
    assert shape["p99_ms"] == 42.0 and shape["staleness_ms"] == 17.0
    assert shape["sample_n"] == 64
    assert shape["sinks"]["sink-1"]["count"] == 9
    assert shape["critical_path"]["dominant"] == "compute"
    assert shape["critical_path"]["dominant_share"] == pytest.approx(0.75)
    assert shape["device_state_bytes"]["panes"] == 512
    # empty rollup: headline dims are None -> the SLO never judges them
    empty = ControllerServer.latency_shape([])
    assert empty["p99_ms"] is None and empty["staleness_ms"] is None


def test_rest_latency_and_slo_roundtrip(tmp_path, monkeypatch):
    import httpx

    from arroyo_tpu.api.rest import ApiServer
    from arroyo_tpu.controller.controller import ControllerServer, Job

    monkeypatch.setenv("CHECKPOINT_URL", f"file://{tmp_path}/ckpt")
    reset_config()

    async def scenario():
        controller = ControllerServer()
        api = ApiServer(controller)
        port = await api.start()
        prog = (Stream.source("impulse", {"event_rate": 0.0,
                                          "message_count": 1,
                                          "batch_size": 1})
                .sink("blackhole", {}))
        job = Job("j-lat", prog, f"file://{tmp_path}/ckpt", 1)
        controller.jobs["j-lat"] = job
        base = f"http://127.0.0.1:{port}"
        try:
            async with httpx.AsyncClient(base_url=base, timeout=30) as c:
                r = await c.get("/v1/jobs/j-lat/slo")
                assert r.status_code == 200
                assert not r.json()["configured"]

                r = await c.put("/v1/jobs/j-lat/slo",
                                json={"p99_ms": 100.0,
                                      "burn_window_secs": 30})
                assert r.status_code == 200
                assert r.json()["slo"]["p99_ms"] == 100.0
                assert job.slo.p99_ms == 100.0

                # unknown keys are a validation error, not a silent drop
                r = await c.put("/v1/jobs/j-lat/slo", json={"bogus": 1})
                assert r.status_code == 422
                r = await c.put("/v1/jobs/j-lat/slo", json={"p99_ms": -5})
                assert r.status_code == 422
                assert job.slo.p99_ms == 100.0  # rejected PUTs change nothing

                job.slo_eval.evaluate(250.0, None)
                r = await c.get("/v1/jobs/j-lat/slo")
                body = r.json()
                assert body["last"]["violating"]
                assert body["violations_total"] == 1

                r = await c.get("/v1/jobs/j-lat/latency")
                assert r.status_code == 200
                data = r.json()
                # which path answered depends on whether the process-
                # wide metrics registry holds rows from earlier tests;
                # both shapes carry the same contract
                assert data["source"] in ("heartbeat", "local_registry")
                assert "sinks" in data and "critical_path" in data
                assert data["slo"]["last"]["violating"]

                r = await c.get("/v1/jobs/no-such-job/latency")
                assert r.status_code == 404
                r = await c.get("/v1/jobs/no-such-job/slo")
                assert r.status_code == 404
        finally:
            await api.stop()

    asyncio.new_event_loop().run_until_complete(scenario())


# -- off-path discipline ------------------------------------------------------


def test_off_path_records_nothing(rng):
    assert latency.active() is None
    assert not latency.sampling_enabled()
    outs = run_pipeline(
        [_events(rng, n=64) for _ in range(3)],
        lambda s: s.map(lambda c: {"k": c["k"], "v": c["v"]}, name="m")
                   .sink("memory", {"name": "out"}))
    assert outs and all(b.lat_stamp is None for b in outs)
    # the engine must not have armed it as a side effect
    assert latency.active() is None
    assert latency.summary_ride_alongs("any") == {}
