"""The control-plane wire IS protobuf per rpc/proto/rpc.proto: a raw
client speaking generated rpc_pb2 messages (no dict layer) interoperates
with the dict-based services — tonic/grpcurl could do the same."""

import asyncio

import numpy as np
import pytest

from arroyo_tpu.rpc.gen import rpc_pb2
from arroyo_tpu.rpc.transport import (
    RpcClient,
    RpcServer,
    dict_to_proto,
    proto_to_dict,
)


def test_dict_proto_roundtrip():
    d = {
        "job_id": "j1", "program": b"\x00\x01pickle",
        "tasks": [{"operator_id": "op1", "subtask_index": 0,
                   "worker_id": "w1"},
                  {"operator_id": "op2", "subtask_index": 3,
                   "worker_id": "w2"}],
        "restore_epoch": 4,
        "worker_data_addrs": {"w1": "127.0.0.1:1", "w2": "127.0.0.1:2"},
        "checkpoint_url": "file:///tmp/x",
    }
    msg = dict_to_proto(rpc_pb2.StartExecutionReq(), d)
    back = proto_to_dict(rpc_pb2.StartExecutionReq.FromString(
        msg.SerializeToString()))
    assert back == d

    # numpy scalars coerce; None means unset; optional stays absent
    msg2 = dict_to_proto(rpc_pb2.StartExecutionReq(), {
        "job_id": "j2", "restore_epoch": None})
    back2 = proto_to_dict(msg2)
    assert "restore_epoch" not in back2
    hb = dict_to_proto(rpc_pb2.HeartbeatReq(),
                       {"worker_id": "w", "time": np.int64(123)})
    assert proto_to_dict(hb)["time"] == 123

    with pytest.raises(KeyError, match="no field"):
        dict_to_proto(rpc_pb2.HeartbeatReq(), {"nope": 1})


def test_raw_protobuf_client_interop():
    """A client that never touches the dict layer — pure rpc_pb2 over
    grpc — talks to the dict-based RpcServer services."""
    import grpc

    async def run():
        seen = {}

        async def register(req):
            seen.update(req)
            return {}

        srv = RpcServer()
        srv.add_service("ControllerGrpc", {"RegisterWorker": register})
        port = await srv.start("127.0.0.1")

        chan = grpc.aio.insecure_channel(f"127.0.0.1:{port}")
        fn = chan.unary_unary(
            "/arroyo_tpu.rpc.ControllerGrpc/RegisterWorker",
            request_serializer=rpc_pb2.RegisterWorkerReq.SerializeToString,
            response_deserializer=rpc_pb2.Empty.FromString)
        resp = await fn(rpc_pb2.RegisterWorkerReq(
            worker_id="w-raw", job_id="j-raw", rpc_address="h:1",
            data_address="h:2", slots=8, run_id="0"))
        assert isinstance(resp, rpc_pb2.Empty)
        await chan.close()
        await srv.stop()
        return seen

    seen = asyncio.run(run())
    assert seen["worker_id"] == "w-raw"
    assert seen["slots"] == 8


def test_dict_client_rejects_schema_violations():
    """Sending a field the proto doesn't declare fails loudly at the
    client — the schema is enforced, not advisory."""
    async def run():
        srv = RpcServer()
        srv.add_service("ControllerGrpc",
                        {"Heartbeat": lambda req: {}})
        port = await srv.start("127.0.0.1")
        client = RpcClient(f"127.0.0.1:{port}", "ControllerGrpc")
        try:
            with pytest.raises(KeyError, match="no field"):
                await client.call("Heartbeat", {"bogus_field": 1})
        finally:
            await client.close()
            await srv.stop()

    asyncio.run(run())
