"""Differential random testing: randomly generated window/aggregate
queries run through the FULL SQL engine and are checked against an
independent pure-python oracle — the breadth net behind the
hand-written correctness suites (arroyo-sql-testing's
correctness_run_codegen analog, generalized).

Deterministic: seeds are fixed per case; failures reproduce by seed.
"""

import numpy as np
import pytest

from arroyo_tpu import Batch
from arroyo_tpu.connectors.memory import clear_sink, sink_output
from arroyo_tpu.engine.engine import LocalRunner
from arroyo_tpu.sql import SchemaProvider, plan_sql

SEC = 1_000_000


def _make_table(rng, n, n_keys, span_secs, null_frac):
    ts = np.sort(rng.integers(0, span_secs * SEC, n)).astype(np.int64)
    k = rng.integers(0, n_keys, n).astype(np.int64)
    v = rng.integers(-1000, 1000, n).astype(np.float64)
    nulls = rng.random(n) < null_frac
    v[nulls] = np.nan
    return ts, k, v


def _windows_of(t, mode, width, slide):
    """Window ends a row at time t contributes to (tumble/hop)."""
    if mode == "tumble":
        return [(t // width + 1) * width]
    out = []
    e = (t // slide + 1) * slide
    while e - width <= t < e:
        out.append(e)
        e += slide
    return out


def _session_windows(times, gap):
    """Gap-merged session (start, end) list for one key's sorted times."""
    sessions = []
    for t in times:
        if sessions and t < sessions[-1][1]:
            s, e = sessions[-1]
            sessions[-1] = (s, max(e, t + gap))
        else:
            sessions.append((t, t + gap))
    return sessions


def _oracle(mode, ts, k, v, width, slide, gap, where_min):
    """{(key, window_end): (cnt_star, cnt_v, sum, min, max, avg)} with
    SQL null-skipping semantics, after `WHERE v >= where_min OR v IS
    NULL` pre-filtering (nulls kept so null-skipping is exercised)."""
    keep = ~(np.nan_to_num(v, nan=where_min) < where_min)
    ts, k, v = ts[keep], k[keep], v[keep]
    cells = {}
    if mode == "session":
        for key in np.unique(k):
            times = ts[k == key]
            for (s, e) in _session_windows(np.sort(times).tolist(), gap):
                sel = (k == key) & (ts >= s) & (ts < e)
                cells[(int(key), e)] = v[sel]
    else:
        tmp = {}
        for t, key, val in zip(ts.tolist(), k.tolist(), v.tolist()):
            for e in _windows_of(t, mode, width, slide):
                tmp.setdefault((key, e), []).append(val)
        cells = {key: np.asarray(vals) for key, vals in tmp.items()}
    out = {}
    for key, vals in cells.items():
        vv = vals[~np.isnan(vals)]
        out[key] = (
            len(vals), len(vv),
            vv.sum() if len(vv) else None,
            vv.min() if len(vv) else None,
            vv.max() if len(vv) else None,
            vv.mean() if len(vv) else None,
        )
    return out


CASES = [
    # (seed, mode, width_s, slide_s, gap_s, n, keys, span_s, null_frac)
    (1, "tumble", 1, 1, None, 3000, 7, 6, 0.0),
    (2, "tumble", 2, 2, None, 5000, 40, 9, 0.3),
    (3, "hop", 2, 1, None, 4000, 12, 7, 0.0),
    (4, "hop", 3, 1, None, 6000, 25, 8, 0.2),
    (5, "hop", 4, 2, None, 2500, 5, 10, 0.5),
    (6, "session", None, None, 1, 2000, 9, 8, 0.0),
    (7, "session", None, None, 2, 3000, 15, 12, 0.25),
    (8, "tumble", 1, 1, None, 800, 3, 3, 0.9),  # nearly-all-null
    (9, "hop", 2, 1, None, 1, 1, 1, 0.0),       # single row
    (10, "session", None, None, 1, 1200, 4, 20, 0.1),  # sparse keys
]


@pytest.mark.parametrize(
    "seed,mode,width_s,slide_s,gap_s,n,keys,span_s,null_frac", CASES,
    ids=[f"s{c[0]}-{c[1]}" for c in CASES])
def test_fuzz_window_aggregates(seed, mode, width_s, slide_s, gap_s, n,
                                keys, span_s, null_frac):
    _run_window_fuzz(seed, mode, width_s, slide_s, gap_s, n, keys,
                     span_s, null_frac)


PARALLEL_CASES = [
    # (seed, mode, width_s, slide_s, gap_s, n, keys, span_s, null_frac,
    #  n_batches, parallelism) — shuffle fan-out + multi-subtask panes
    (61, "tumble", 2, 2, None, 5000, 30, 9, 0.2, 5, 2),
    (62, "hop", 3, 1, None, 4000, 12, 8, 0.0, 4, 3),
    (63, "session", None, None, 1, 2500, 10, 25, 0.15, 6, 2),
    (64, "hop", 2, 1, None, 3000, 40, 7, 0.5, 3, 2),
]


@pytest.mark.parametrize(
    "seed,mode,width_s,slide_s,gap_s,n,keys,span_s,null_frac,nb,par",
    PARALLEL_CASES, ids=[f"s{c[0]}-{c[1]}-p{c[10]}"
                         for c in PARALLEL_CASES])
def test_fuzz_window_aggregates_parallel(seed, mode, width_s, slide_s,
                                         gap_s, n, keys, span_s,
                                         null_frac, nb, par):
    """The same differential window fuzz through SHUFFLED multi-subtask
    plans: batches split across arrivals, query_parallelism > 1 — the
    fan-in watermark and per-subtask pane paths must still match the
    single-threaded oracle exactly."""
    _run_window_fuzz(seed, mode, width_s, slide_s, gap_s, n, keys,
                     span_s, null_frac, n_batches=nb, parallelism=par)


RING_CASES = [
    # (seed, width_s, slide_s, n, keys, span_s, null_frac) — W >= 64 so
    # fire_panes takes the bin-sharded ring emission on the 8-dev mesh
    (41, 100, 1, 4000, 9, 220, 0.2),
    (42, 300, 1, 2500, 5, 650, 0.0),
    (43, 128, 2, 3000, 20, 500, 0.4),
]


@pytest.mark.parametrize(
    "seed,width_s,slide_s,n,keys,span_s,null_frac",
    # the W100/W300 cases span hundreds of seconds of event time
    # through wide rings — the heaviest fuzz cases; W64 keeps the ring
    # path covered in tier-1
    [pytest.param(*c, marks=pytest.mark.slow) if c[1] // c[2] >= 100
     else c for c in RING_CASES],
    ids=[f"s{c[0]}-W{c[1] // c[2]}" for c in RING_CASES])
def test_fuzz_long_window_ring_path(seed, width_s, slide_s, n, keys,
                                    span_s, null_frac, monkeypatch):
    """Same differential window fuzz, forced through the ring-pane
    emission (long-window bin-sharding, ops/keyed_bins._emit_ring)."""
    monkeypatch.setenv("ARROYO_RING", "on")
    _run_window_fuzz(seed, "hop", width_s, slide_s, None, n, keys,
                     span_s, null_frac)


def _run_window_fuzz(seed, mode, width_s, slide_s, gap_s, n,
                     keys, span_s, null_frac, n_batches=1,
                     parallelism=1):
    from arroyo_tpu.sql.planner import Planner

    rng = np.random.default_rng(seed)
    ts, k, v = _make_table(rng, n, keys, span_s, null_frac)
    where_min = float(rng.integers(-500, 0))

    bounds = np.linspace(0, n, n_batches + 1).astype(int)
    p = SchemaProvider()
    p.add_memory_table("t", {"k": "i", "v": "f"}, [
        Batch(ts[a:b], {"k": k[a:b], "v": v[a:b]})
        for a, b in zip(bounds[:-1], bounds[1:]) if b > a])
    if mode == "tumble":
        win = f"TUMBLE(INTERVAL '{width_s}' SECOND)"
    elif mode == "hop":
        win = (f"HOP(INTERVAL '{slide_s}' SECOND, "
               f"INTERVAL '{width_s}' SECOND)")
    else:
        win = f"SESSION(INTERVAL '{gap_s}' SECOND)"
    sql = f"""
    SELECT k, {win} as window,
           count(*) as c_star, count(v) as c_v,
           sum(v) as s, min(v) as lo, max(v) as hi, avg(v) as mean
    FROM t WHERE v >= {where_min} OR v IS NULL
    GROUP BY 1, 2
    """
    clear_sink("results")
    prog = Planner(p).plan(sql, query_parallelism=parallelism)
    # every fuzz-generated plan must pass graph-level validation (the
    # same gate Engine applies before building operators)
    from arroyo_tpu.analysis.plan_validator import (
        errors_of,
        validate_program,
    )

    assert not errors_of(validate_program(prog)), (
        seed, [d.render() for d in validate_program(prog)])
    LocalRunner(prog).run()
    outs = sink_output("results")
    out = Batch.concat(outs) if outs else None

    exp = _oracle(mode, ts, k, v,
                  (width_s or 0) * SEC, (slide_s or 0) * SEC,
                  (gap_s or 0) * SEC, where_min)
    got = {}
    if out is not None:
        for j in range(len(out)):
            key = (int(out.columns["k"][j]),
                   int(out.columns["window_end"][j]))
            assert key not in got, f"window emitted twice: {key}"
            got[key] = j
    assert set(got) == set(exp), (
        f"seed {seed}: windows differ "
        f"(missing {sorted(set(exp) - set(got))[:5]}, "
        f"extra {sorted(set(got) - set(exp))[:5]})")
    for key, (c_star, c_v, s_, lo, hi, mean) in exp.items():
        j = got[key]
        assert int(out.columns["c_star"][j]) == c_star, (seed, key)
        assert int(out.columns["c_v"][j]) == c_v, (seed, key)
        for col, want in (("s", s_), ("lo", lo), ("hi", hi),
                          ("mean", mean)):
            have = out.columns[col][j]
            if want is None:
                assert np.isnan(have), (seed, key, col, have)
            else:
                assert have == pytest.approx(want, rel=1e-9, abs=1e-9), (
                    seed, key, col, have, want)


@pytest.mark.parametrize("mutation", ["drop_shuffle", "key_mismatch",
                                      "orphan"])
@pytest.mark.parametrize("seed", [1, 2])
def test_fuzz_plan_validator_rejects_mutations(seed, mutation):
    """Fuzz-generated plans pass the plan validator untouched (asserted
    inside _run_window_fuzz); the SAME plans with a seeded mutation —
    a dropped shuffle edge, a mismatched join key schema, an orphaned
    node — must be rejected with the matching diagnostic code."""
    from arroyo_tpu.analysis.plan_validator import (
        PlanValidationError,
        check_program,
        errors_of,
        validate_program,
    )
    from arroyo_tpu.graph.logical import (
        ColumnExpr,
        EdgeType,
        LogicalOperator,
        OpKind,
    )
    from arroyo_tpu.sql.planner import Planner

    rng = np.random.default_rng(seed)
    ts, k, v = _make_table(rng, 2000, 9, 6, 0.1)
    p = SchemaProvider()
    p.add_memory_table("t", {"k": "i", "v": "f"},
                       [Batch(ts, {"k": k, "v": v})])
    p.add_memory_table("u", {"k": "i", "w": "f"},
                       [Batch(ts, {"k": k, "w": v})])
    if mutation == "key_mismatch":
        sql = """
        SELECT a.k as k, a.c as c, b.d as d
        FROM (SELECT k, TUMBLE(INTERVAL '1' SECOND) as window,
                     count(*) as c FROM t GROUP BY 1, 2) a
        JOIN (SELECT k, TUMBLE(INTERVAL '1' SECOND) as window,
                     count(*) as d FROM u GROUP BY 1, 2) b
        ON a.k = b.k AND a.window = b.window
        """
    else:
        sql = """
        SELECT k, TUMBLE(INTERVAL '1' SECOND) as window, count(*) as c
        FROM t GROUP BY 1, 2
        """
    prog = Planner(p).plan(sql, query_parallelism=2)
    assert not errors_of(validate_program(prog))  # valid as planned

    if mutation == "drop_shuffle":
        for src, dst, data in prog.graph.edges(data=True):
            node = prog.node(dst)
            if (data["edge"].typ is EdgeType.SHUFFLE
                    and node.max_parallelism != 1
                    and node.operator.kind
                    in (OpKind.TUMBLING_WINDOW_AGGREGATOR,
                        OpKind.WINDOW)):
                data["edge"].typ = EdgeType.FORWARD
                break
        else:
            raise AssertionError("no shuffle edge found to mutate")
        want = "keyed-not-shuffled"
    elif mutation == "key_mismatch":
        for src, dst, data in prog.graph.edges(data=True):
            if data["edge"].typ is EdgeType.SHUFFLE_JOIN_RIGHT:
                data["edge"].key_schema = "k,extra_col"
                break
        else:
            raise AssertionError("no join edge found to mutate")
        want = "key-schema-mismatch"
    else:  # orphan: a node whose inputs were dropped entirely
        prog.add_node(LogicalOperator(
            OpKind.EXPRESSION, "orphan",
            expr=ColumnExpr("orphan", lambda c: c)))
        want = "dangling-node"

    errs = errors_of(validate_program(prog))
    assert any(d.code == want for d in errs), (mutation, errs)
    with pytest.raises(PlanValidationError):
        check_program(prog)


@pytest.mark.parametrize("seed", [51, 52, 53, 54])
def test_fuzz_group_by_window_consolidation(seed):
    """Random GROUP BY-window re-aggregations (q5 MaxBids shape) at
    random parallelism and batch splits: exactly ONE final row per
    window, values matching the oracle — the watermark-consolidation
    invariant under every interleaving."""
    import collections

    from arroyo_tpu.sql.planner import Planner

    rng = np.random.default_rng(seed)
    n = int(rng.integers(1500, 6000))
    width_s = int(rng.integers(1, 4))
    nkeys = int(rng.integers(3, 25))
    par = int(rng.integers(1, 4))
    agg = rng.choice(["max", "min", "sum"])
    nbatch = int(rng.integers(1, 7))
    ts = np.sort(rng.integers(0, 8 * SEC, n)).astype(np.int64)
    k = rng.integers(0, nkeys, n).astype(np.int64)
    bounds = np.linspace(0, n, nbatch + 1).astype(int)
    provider = SchemaProvider()
    provider.add_memory_table("events", {"k": "i"}, [
        Batch(ts[a:b], {"k": k[a:b]})
        for a, b in zip(bounds[:-1], bounds[1:]) if b > a])
    clear_sink("results")
    prog = Planner(provider).plan(f"""
        SELECT {agg}(num) AS m, window FROM (
          SELECT count(*) AS num,
                 TUMBLE(INTERVAL '{width_s}' SECOND) AS window
          FROM events GROUP BY k, 2
        ) GROUP BY 2
    """, query_parallelism=par)
    LocalRunner(prog).run()
    out = Batch.concat(sink_output("results"))
    per_w = collections.Counter(int(w) for w in out.columns["window_end"])
    assert all(v == 1 for v in per_w.values()), (seed, per_w)
    want = collections.defaultdict(collections.Counter)
    for t, kk in zip(ts.tolist(), k.tolist()):
        wend = (t // (width_s * SEC) + 1) * width_s * SEC
        want[wend][kk] += 1
    assert set(per_w) == set(want), seed
    fn = {"max": max, "min": min, "sum": sum}[agg]
    got = {int(w): int(m) for w, m in zip(out.columns["window_end"],
                                          out.columns["m"])}
    for wend, cnt in want.items():
        assert got[wend] == fn(cnt.values()), (seed, agg, wend)


@pytest.mark.parametrize("device_join", ["off", "on"])
@pytest.mark.parametrize("seed", [11, 12, 13])
def test_fuzz_windowed_join(seed, device_join, monkeypatch):
    """Random windowed equi-joins (q8 shape) against a set oracle —
    both the host numpy path and the device sort/probe/expand kernels
    (ops/join.py) must produce identical results."""
    monkeypatch.setenv("ARROYO_DEVICE_JOIN", device_join)
    rng = np.random.default_rng(seed)
    n = int(rng.integers(500, 3000))
    ts_a, ka, _ = _make_table(rng, n, int(rng.integers(3, 20)), 6, 0.0)
    ts_b, kb, _ = _make_table(rng, n, int(rng.integers(3, 20)), 6, 0.0)

    p = SchemaProvider()
    p.add_memory_table("a", {"u": "i"}, [Batch(ts_a, {"u": ka})])
    p.add_memory_table("b", {"s": "i"}, [Batch(ts_b, {"s": kb})])
    sql = """
    SELECT P.u as u, P.np as np, A.na as na
    FROM (SELECT u, TUMBLE(INTERVAL '1' SECOND) as window, count(*) as np
          FROM a GROUP BY 1, 2) AS P
    JOIN (SELECT s, TUMBLE(INTERVAL '1' SECOND) as window, count(*) as na
          FROM b GROUP BY 1, 2) AS A
    ON P.u = A.s and P.window = A.window
    """
    clear_sink("results")
    LocalRunner(plan_sql(sql, p)).run()
    outs = sink_output("results")

    def counts(ts, k):
        out = {}
        for t, key in zip(ts.tolist(), k.tolist()):
            e = (t // SEC + 1) * SEC
            out[(key, e)] = out.get((key, e), 0) + 1
        return out

    ca, cb = counts(ts_a, ka), counts(ts_b, kb)
    exp = {kw: (ca[kw], cb[kw]) for kw in set(ca) & set(cb)}
    got = {}
    for b in outs:
        for j in range(len(b)):
            kw = (int(b.columns["u"][j]), int(b.timestamp[j]) + 1)
            got[kw] = (int(b.columns["np"][j]), int(b.columns["na"][j]))
    assert got == exp, f"seed {seed}"


@pytest.mark.parametrize("device_join", ["off", "on"])
@pytest.mark.parametrize("seed,kind", [
    (21, "LEFT"), (22, "RIGHT"), (23, "FULL"),
    (24, "LEFT"), (25, "FULL")])
def test_fuzz_outer_join_net_result(seed, kind, device_join, monkeypatch):
    """Random LEFT/RIGHT/FULL joins: after applying __op retractions,
    the net row multiset must equal the standard SQL outer-join result
    regardless of arrival interleaving."""
    from collections import Counter

    monkeypatch.setenv("ARROYO_DEVICE_JOIN", device_join)
    rng = np.random.default_rng(seed)
    nl = int(rng.integers(5, 60))
    nr = int(rng.integers(5, 60))
    lids = rng.integers(0, 20, nl).astype(np.int64)
    rids = rng.integers(0, 20, nr).astype(np.int64)
    lvs = rng.integers(0, 1000, nl).astype(np.int64)
    rvs = rng.integers(0, 1000, nr).astype(np.int64)

    p = SchemaProvider()
    p.add_memory_table("l", {"id": "i", "lv": "i"}, [
        Batch(np.sort(rng.integers(0, 1000, nl)).astype(np.int64),
              {"id": lids, "lv": lvs})])
    p.add_memory_table("r", {"id": "i", "rv": "i"}, [
        Batch(np.sort(rng.integers(0, 1000, nr)).astype(np.int64),
              {"id": rids, "rv": rvs})])
    clear_sink("results")
    LocalRunner(plan_sql(
        f"SELECT l.id as lid, r.id as rid, lv, rv FROM l "
        f"{kind} JOIN r ON l.id = r.id", p)).run()
    outs = sink_output("results")

    def cell(x):
        return None if (isinstance(x, float) and np.isnan(x)) else int(x)

    net = Counter()
    for b in outs:
        ops = b.columns["__op"]
        for j in range(len(b)):
            row = tuple(cell(b.columns[c][j])
                        for c in ("lid", "rid", "lv", "rv"))
            if int(ops[j]) == 2:
                net[row] -= 1
            else:
                net[row] += 1
    net = +net  # drop zero entries

    exp = Counter()
    r_by_id = {}
    for i in range(nr):
        r_by_id.setdefault(int(rids[i]), []).append(int(rvs[i]))
    for i in range(nl):
        lid, lv = int(lids[i]), int(lvs[i])
        if lid in r_by_id:
            for rv in r_by_id[lid]:
                exp[(lid, lid, lv, rv)] += 1
        elif kind in ("LEFT", "FULL"):
            exp[(lid, None, lv, None)] += 1
    if kind in ("RIGHT", "FULL"):
        lkeys = set(lids.tolist())
        for i in range(nr):
            rid, rv = int(rids[i]), int(rvs[i])
            if rid not in lkeys:
                exp[(None, rid, None, rv)] += 1
    assert net == exp, (
        f"seed {seed} {kind}: net/exp differ "
        f"(net-exp={+(net - exp)!r}, exp-net={+(exp - net)!r})")


@pytest.mark.parametrize("seed", [
    31, pytest.param(32, marks=pytest.mark.slow), 33, 34, 35, 36, 37])
def test_fuzz_checkpoint_restore_exactly_once(seed, tmp_path):
    """Random pipeline shapes x random crash points: checkpoint, crash,
    restore — output must be exactly-once (no gaps, no duplicates)
    whatever window type, parallelism, or crash timing the seed drew."""
    import asyncio
    import json as _json

    from arroyo_tpu import AggKind, AggSpec, SessionWindow, Stream
    from arroyo_tpu.engine.engine import Engine
    from arroyo_tpu.types import StopMode

    rng = np.random.default_rng(seed)
    total = int(rng.integers(2000, 5000))
    n_buckets = int(rng.integers(3, 11))
    par = int(rng.integers(1, 3))
    mode = ["tumble", "slide", "session"][int(rng.integers(0, 3))]
    crash_after = float(rng.uniform(0.02, 0.12))
    url = f"file://{tmp_path}/ckpt"
    out_path = f"{tmp_path}/out.jsonl"
    job = f"fuzz-restore-{seed}"

    def build():
        s = (Stream.source("impulse", {
                "event_rate": 40_000.0, "message_count": total,
                "event_time_interval_micros": 1000, "batch_size": 128},
                parallelism=par)
             .watermark(max_lateness_micros=0)
             .map(lambda c: {"counter": c["counter"],
                             "bucket": c["counter"] % n_buckets}, name="b")
             .key_by("bucket"))
        aggs = [AggSpec(AggKind.COUNT, None, "cnt"),
                AggSpec(AggKind.SUM, "counter", "sum_c")]
        if mode == "tumble":
            s = s.tumbling_aggregate(100 * 1000, aggs)
        elif mode == "slide":
            s = s.sliding_aggregate(200 * 1000, 100 * 1000, aggs)
        else:
            s = s.window(SessionWindow(50 * 1000), aggs)
        return s.sink("single_file", {"path": out_path}, parallelism=1)

    async def run_with_crash():
        """Crash mid-stream after checkpoint 1; returns False when the
        bounded stream finished before the crash landed (machine-load
        dependent) — the restore phase is skipped in that case."""
        eng = Engine.for_local(build(), job, checkpoint_url=url)
        running = eng.start()
        join_t = asyncio.ensure_future(running.join())
        await asyncio.sleep(crash_after)
        if join_t.done():
            return False
        await running.checkpoint(1)
        ok = await running.wait_for_checkpoint(1)
        if not ok or join_t.done():
            # stream drained before the barrier sealed: nothing to crash
            await asyncio.wait([join_t])
            return False
        await running.stop(StopMode.IMMEDIATE)
        try:
            await join_t
        except RuntimeError:
            pass
        return True

    async def run_restored():
        eng = Engine.for_local(build(), job, checkpoint_url=url,
                               restore_epoch=1)
        await eng.start().join()

    crashed = asyncio.run(run_with_crash())
    if crashed:
        asyncio.run(run_restored())

    rows = [_json.loads(line) for line in open(out_path)]
    mult = 2 if mode == "slide" else 1  # each event feeds width/slide panes
    assert sum(r["cnt"] for r in rows) == total * mult, (seed, mode)
    # impulse splits message_count across subtasks and each split's
    # counter restarts at 0
    splits = [total // par + (1 if i < total % par else 0)
              for i in range(par)]
    exp_sum = mult * sum(c * (c - 1) // 2 for c in splits)
    assert sum(r["sum_c"] for r in rows) == exp_sum, (seed, mode)
    seen = set()
    for r in rows:
        key = (r["bucket"], r["window_end"])
        assert key not in seen, f"duplicate emission {key} (seed {seed})"
        seen.add(key)


@pytest.mark.parametrize("seed", [41, 42, 43, 44])
def test_fuzz_distinct_udaf_having(seed):
    """The buffered (non-mergeable) window path: COUNT(DISTINCT), a
    median UDAF, and HAVING, against a python oracle."""
    rng = np.random.default_rng(seed)
    n = int(rng.integers(800, 4000))
    keys = int(rng.integers(3, 12))
    width_s = int(rng.integers(1, 4))
    having_min = int(rng.integers(2, 12))
    ts, k, _ = _make_table(rng, n, keys, 8, 0.0)
    # small domain -> dups; a null fraction pins SQL null semantics:
    # COUNT(DISTINCT) excludes NULLs (pre-fix, NaN != NaN made every
    # null row its own "distinct" value), UDAFs see non-null rows only
    v = rng.integers(0, 25, n).astype(np.float64)
    v[rng.random(n) < 0.2] = np.nan

    from arroyo_tpu.sql.functions import UDAFS

    p = SchemaProvider()
    if "med" not in UDAFS:  # registration is global across param cases
        p.register_udaf("med", np.median)
    p.add_memory_table("t", {"k": "i", "v": "f"},
                       [Batch(ts, {"k": k, "v": v})])
    sql = f"""
    SELECT k, TUMBLE(INTERVAL '{width_s}' SECOND) as window,
           count(distinct v) as dv, med(v) as med, count(*) as c
    FROM t GROUP BY 1, 2 HAVING count(*) >= {having_min}
    """
    clear_sink("results")
    LocalRunner(plan_sql(sql, p)).run()
    outs = sink_output("results")
    out = Batch.concat(outs) if outs else None

    width = width_s * SEC
    cells = {}
    for t_, key, val in zip(ts.tolist(), k.tolist(), v.tolist()):
        (e,) = _windows_of(t_, "tumble", width, None)
        cells.setdefault((key, e), []).append(val)

    def cell_exp(vals):
        vv = [x for x in vals if not np.isnan(x)]
        return (len(set(vv)),
                float(np.median(vv)) if vv else float("nan"),
                len(vals))

    exp = {key: cell_exp(vals)
           for key, vals in cells.items() if len(vals) >= having_min}

    got = {}
    if out is not None:
        for j in range(len(out)):
            key = (int(out.columns["k"][j]),
                   int(out.columns["window_end"][j]))
            assert key not in got
            got[key] = (int(out.columns["dv"][j]),
                        float(out.columns["med"][j]),
                        int(out.columns["c"][j]))
    assert set(got) == set(exp), f"seed {seed}"
    for key in exp:
        assert got[key][0] == exp[key][0], (seed, key, "distinct")
        assert got[key][1] == pytest.approx(exp[key][1], nan_ok=True), \
            (seed, key, "med")
        assert got[key][2] == exp[key][2], (seed, key, "count")


def _gen_expr(rng, depth):
    """Random scalar expression tree -> (sql_text, python_eval_fn).
    eval fn takes (k:int, v:float-or-None) and returns the SQL
    three-valued result (None = NULL)."""
    def num_leaf():
        c = int(rng.integers(0, 3))
        if c == 0:
            return "k", lambda k, v: k
        if c == 1:
            return "v", lambda k, v: v
        lit = int(rng.integers(-20, 20))
        return str(lit), lambda k, v, _l=lit: _l

    if depth <= 0:
        return num_leaf()
    c = int(rng.integers(0, 4))
    if c == 0:  # arithmetic
        ls, lf = _gen_expr(rng, depth - 1)
        rs, rf = _gen_expr(rng, depth - 1)
        op = ["+", "-", "*"][int(rng.integers(0, 3))]
        pyop = {"+": lambda a, b: a + b, "-": lambda a, b: a - b,
                "*": lambda a, b: a * b}[op]

        def f(k, v, _lf=lf, _rf=rf, _o=pyop):
            a, b = _lf(k, v), _rf(k, v)
            return None if a is None or b is None else _o(a, b)
        return f"({ls} {op} {rs})", f
    if c == 1:  # CASE WHEN cmp THEN x ELSE y END
        ls, lf = _gen_expr(rng, depth - 1)
        rs, rf = _gen_expr(rng, depth - 1)
        xs, xf = _gen_expr(rng, depth - 1)
        ys, yf = _gen_expr(rng, depth - 1)
        op = ["<", ">", "=", "<=", ">="][int(rng.integers(0, 5))]
        pyop = {"<": lambda a, b: a < b, ">": lambda a, b: a > b,
                "=": lambda a, b: a == b, "<=": lambda a, b: a <= b,
                ">=": lambda a, b: a >= b}[op]

        def f(k, v, _lf=lf, _rf=rf, _xf=xf, _yf=yf, _o=pyop):
            a, b = _lf(k, v), _rf(k, v)
            cond = None if a is None or b is None else _o(a, b)
            # SQL: NULL condition selects the ELSE branch
            return _xf(k, v) if cond else _yf(k, v)
        return (f"(CASE WHEN {ls} {op} {rs} THEN {xs} ELSE {ys} END)", f)
    if c == 2:  # COALESCE
        ls, lf = _gen_expr(rng, depth - 1)
        rs, rf = _gen_expr(rng, depth - 1)

        def f(k, v, _lf=lf, _rf=rf):
            a = _lf(k, v)
            return a if a is not None else _rf(k, v)
        return f"COALESCE({ls}, {rs})", f
    # ABS
    ls, lf = _gen_expr(rng, depth - 1)

    def f(k, v, _lf=lf):
        a = _lf(k, v)
        return None if a is None else abs(a)
    return f"ABS({ls})", f


@pytest.mark.parametrize("seed", list(range(51, 91)))
def test_fuzz_scalar_expressions(seed):
    """Random expression trees (arithmetic, CASE, COALESCE, ABS) over a
    nullable float column, evaluated through the full engine and checked
    row-by-row against a python three-valued-logic interpreter."""
    rng = np.random.default_rng(seed)
    n = 400
    ts = np.arange(n, dtype=np.int64) * 100
    k = rng.integers(-10, 10, n).astype(np.int64)
    v = rng.integers(-50, 50, n).astype(np.float64)
    v[rng.random(n) < 0.3] = np.nan

    sql_e, f = _gen_expr(rng, 3)
    p = SchemaProvider()
    p.add_memory_table("t", {"k": "i", "v": "f"},
                       [Batch(ts, {"k": k, "v": v})])
    clear_sink("results")
    LocalRunner(plan_sql(
        f"SELECT k, v, {sql_e} as e FROM t", p)).run()
    out = Batch.concat(sink_output("results"))
    assert len(out) == n
    # rows keep source order per batch; match by (k, v) row identity via
    # the original index column k/v pairs in order
    for j in range(n):
        kk = int(out.columns["k"][j])
        vv = out.columns["v"][j]
        vv = None if (isinstance(vv, float) and np.isnan(vv)) else float(vv)
        want = f(kk, vv)
        have = out.columns["e"][j]
        if want is None:
            assert (have is None
                    or (isinstance(have, float) and np.isnan(have))), (
                seed, sql_e, j, kk, vv, have)
        else:
            assert have == pytest.approx(float(want), rel=1e-9), (
                seed, sql_e, j, kk, vv, have, want)


@pytest.mark.parametrize("seed", [61, 62, 63, 64, 65, 66])
def test_fuzz_rescale_reshard(seed):
    """Random N->M rescales mid-stream: snapshot N KeyedBinState
    partitions, re-shard to M by key range (filter + merge, the
    restore-time re-partitioning path), finish the stream, and compare
    every pane against the oracle — duplicates and losses both fail."""
    from arroyo_tpu.graph.logical import AggKind, AggSpec
    from arroyo_tpu.ops.keyed_bins import (
        KeyedBinState,
        filter_canonical_snapshot,
        merge_canonical_snapshots,
    )
    from arroyo_tpu.types import hash_columns, range_for_server

    rng = np.random.default_rng(seed)
    n_from = int(rng.integers(1, 5))
    n_to = int(rng.integers(1, 5))
    n = int(rng.integers(1500, 4000))
    n_keys = int(rng.integers(5, 40))
    width_s = int(rng.integers(1, 4))
    aggs = (AggSpec(AggKind.COUNT, None, "cnt"),
            AggSpec(AggKind.SUM, "v", "total"),
            AggSpec(AggKind.MIN, "v", "lo"),
            AggSpec(AggKind.MAX, "v", "hi"))

    ts = np.sort(rng.integers(0, 6 * SEC, n)).astype(np.int64)
    k = rng.integers(0, n_keys, n).astype(np.int64)
    v = rng.integers(-100, 100, n).astype(np.int64)
    kh = hash_columns([k])
    half = n // 2
    width = width_s * SEC

    def owner(khs, n_parts, idx):
        lo, hi = range_for_server(idx, n_parts)
        return (khs >= np.uint64(lo)) & (khs <= np.uint64(hi))

    got = {}

    def drain(f):
        if f is None:
            return
        kk, oc, wend, _ = f
        for j in range(len(kk)):
            key = (int(kk[j]), int(wend[j]))
            assert key not in got, f"pane duplicated across shards: {key}"
            got[key] = (int(oc["cnt"][j]), int(oc["total"][j]),
                        int(oc["lo"][j]), int(oc["hi"][j]))

    # phase 1: N partitions consume the first half, fire to mid watermark
    wm = int(ts[half - 1]) - width  # behind: keep panes open across rescale
    snaps = []
    for i in range(n_from):
        own = owner(kh[:half], n_from, i)
        st = KeyedBinState(aggs, SEC, width, capacity=32)
        if own.any():
            st.update(kh[:half][own], ts[:half][own],
                      {"v": v[:half][own]})
        drain(st.fire_panes(wm))
        snaps.append({kk_: np.asarray(v_) for kk_, v_ in
                      st.snapshot().items()})

    # phase 2: M partitions each restore the merged overlap of ALL
    # parents filtered to their own range, then consume the second half
    for i in range(n_to):
        merged: dict = {}
        for s in snaps:
            part = filter_canonical_snapshot(
                s, range_for_server(i, n_to))
            merged = merge_canonical_snapshots(merged, part)
        st = KeyedBinState(aggs, SEC, width, capacity=32)
        if merged:
            st.restore(merged)
        own = owner(kh[half:], n_to, i)
        if own.any():
            st.update(kh[half:][own], ts[half:][own],
                      {"v": v[half:][own]})
        drain(st.fire_panes(1 << 60, final=True))

    exp = {}
    for t, key, val in zip(ts.tolist(), kh.tolist(), v.tolist()):
        e = (t // SEC + 1) * SEC
        while e - width <= t < e:
            c, s_, lo, hi = exp.get((key, e), (0, 0, 1 << 60, -(1 << 60)))
            exp[(key, e)] = (c + 1, s_ + val, min(lo, val), max(hi, val))
            e += SEC
    assert got == exp, (
        f"seed {seed} {n_from}->{n_to}: "
        f"missing {len(set(exp) - set(got))}, extra {len(set(got) - set(exp))}")


@pytest.mark.parametrize("seed", [71, 72, 73, 74])
def test_fuzz_multi_source_fanin_no_drops_within_lateness(seed):
    """Two sources with skewed time bases and shuffled batch arrivals,
    UNION ALL'd into one window aggregate: the fan-in watermark is the
    MIN across sources, so every row within the configured lateness
    must be aggregated — no interleaving may drop data or fire a pane
    early.  Oracle = exact per-(key, window) counts over both streams."""
    import collections

    rng = np.random.default_rng(seed)
    na, nb = int(rng.integers(800, 2500)), int(rng.integers(800, 2500))
    skew = int(rng.integers(0, 3)) * SEC  # source b lags by up to 2s
    lateness = 4 * SEC                    # > skew + batch disorder
    width_s = int(rng.integers(1, 4))
    nkeys = int(rng.integers(3, 15))

    def mk(n, base):
        ts = base + np.sort(rng.integers(0, 6 * SEC, n)).astype(np.int64)
        k = rng.integers(0, nkeys, n).astype(np.int64)
        nb_ = int(rng.integers(2, 6))
        bounds = np.linspace(0, n, nb_ + 1).astype(int)
        return ts, k, [Batch(ts[x:y], {"k": k[x:y]})
                       for x, y in zip(bounds[:-1], bounds[1:]) if y > x]

    ts_a, k_a, batches_a = mk(na, 0)
    ts_b, k_b, batches_b = mk(nb, skew)
    p = SchemaProvider()
    p.add_memory_table("a", {"k": "i"}, batches_a,
                       lateness_micros=lateness)
    p.add_memory_table("b", {"k": "i"}, batches_b,
                       lateness_micros=lateness)
    clear_sink("results")
    LocalRunner(plan_sql(f"""
        SELECT k, TUMBLE(INTERVAL '{width_s}' SECOND) as window,
               count(*) as cnt
        FROM (SELECT k FROM a UNION ALL SELECT k FROM b)
        GROUP BY 1, 2
    """, p)).run()
    out = Batch.concat(sink_output("results"))
    exp = collections.Counter()
    for ts, k in ((ts_a, k_a), (ts_b, k_b)):
        for t, kk in zip(ts.tolist(), k.tolist()):
            exp[(int(kk), (t // (width_s * SEC) + 1) * width_s * SEC)] += 1
    got = {}
    for j in range(len(out)):
        key = (int(out.columns["k"][j]), int(out.columns["window_end"][j]))
        assert key not in got, f"pane fired twice: {key}"
        got[key] = int(out.columns["cnt"][j])
    assert got == dict(exp), (
        f"seed {seed}: missing {sorted(set(exp) - set(got))[:5]}, "
        f"extra {sorted(set(got) - set(exp))[:5]}")


@pytest.mark.parametrize("seed,shape", [
    (81, "order_limit"), (82, "row_number"), (83, "order_limit"),
    (84, "row_number"), (85, "row_number")])
def test_fuzz_windowed_topn(seed, shape):
    """Random windowed TopN: both the fused ORDER BY-LIMIT plan and the
    ROW_NUMBER() OVER rewrite, random window kinds/limits/key skew.
    Per window: at most k rows, the returned counts are exactly the
    true top-k multiset, and each returned key's count is its own."""
    import collections

    rng = np.random.default_rng(seed)
    n = int(rng.integers(1500, 5000))
    nkeys = int(rng.integers(4, 40))
    k_lim = int(rng.integers(1, 5))
    width_s = int(rng.integers(1, 4)) * 2
    slide_s = width_s if rng.random() < 0.5 else width_s // 2
    ts = np.sort(rng.integers(0, 8 * SEC, n)).astype(np.int64)
    keys = (rng.zipf(1.3, n) % nkeys).astype(np.int64)  # skewed
    p = SchemaProvider()
    p.add_memory_table("t", {"k": "i"}, [Batch(ts, {"k": keys})])
    win = (f"TUMBLE(INTERVAL '{width_s}' SECOND)" if slide_s == width_s
           else f"HOP(INTERVAL '{slide_s}' SECOND, "
                f"INTERVAL '{width_s}' SECOND)")
    if shape == "order_limit":
        sql = f"""
        CREATE TABLE out WITH (connector='memory', name='results');
        INSERT INTO out
        SELECT k, {win} as window, count(*) as num
        FROM t GROUP BY 1, 2 ORDER BY num DESC LIMIT {k_lim}
        """
    else:
        sql = f"""
        CREATE TABLE out WITH (connector='memory', name='results');
        INSERT INTO out
        SELECT k, num, window FROM (
          SELECT k, count(*) AS num, {win} as window,
                 ROW_NUMBER() OVER (PARTITION BY window
                                    ORDER BY num DESC) as rn
          FROM t GROUP BY 1, 3
        ) WHERE rn <= {k_lim}
        """
    clear_sink("results")
    LocalRunner(plan_sql(sql, p)).run()
    out = Batch.concat(sink_output("results"))
    want = collections.defaultdict(collections.Counter)
    W = width_s * SEC
    S = slide_s * SEC
    for t, kk in zip(ts.tolist(), keys.tolist()):
        e = (t // S + 1) * S
        while e - W <= t < e:
            want[e][kk] += 1
            e += S
    per_w = collections.defaultdict(list)
    for i in range(len(out)):
        per_w[int(out.columns["window_end"][i])].append(
            (int(out.columns["k"][i]), int(out.columns["num"][i])))
    assert set(per_w) <= set(want), seed
    # every window with data must appear (top-k of a non-empty window
    # is non-empty)
    assert set(per_w) == set(want), (
        f"seed {seed}: missing windows {sorted(set(want) - set(per_w))[:4]}")
    for wend, rows_ in per_w.items():
        assert len(rows_) <= k_lim, (seed, wend)
        true_top = sorted(want[wend].values(), reverse=True)[:k_lim]
        assert sorted((c for _, c in rows_), reverse=True) == true_top, (
            seed, wend)
        for kk, c in rows_:
            assert want[wend][kk] == c, (seed, wend, kk)


@pytest.mark.parametrize("seed", [91, 92, 93])
def test_fuzz_session_max_size_clamp(seed):
    """Sessions chaining across the 24h MAX_SESSION_SIZE clamp: random
    near-gap spacings force chains that the engine must split exactly
    where the incremental per-event clamp splits them.  Oracle replays
    the reference's windows.rs clamp semantics event by event."""
    import collections

    from arroyo_tpu.engine.operators_window import MAX_SESSION_SIZE_MICROS

    rng = np.random.default_rng(seed)
    MAX = MAX_SESSION_SIZE_MICROS
    gap_s = int(rng.integers(2, 10))
    gap = gap_s * SEC
    nkeys = 3
    ts_parts, k_parts = [], []
    for key in range(nkeys):
        # a chain that crosses the clamp: spacings mostly just under the
        # gap, sprinkled with over-gap breaks
        m = int(rng.integers(40, 90))
        steps = rng.integers(1, gap + gap // 4, m)  # some exceed gap
        base = int(rng.integers(0, 5 * SEC))
        # scale the chain so cumulative span crosses MAX at least once
        scale = max(1, int((MAX * 1.5) // max(int(steps.sum()), 1)))
        t = base + np.cumsum(steps.astype(np.int64) * scale)
        # re-derive effective spacings vs gap after scaling: keep raw
        ts_parts.append(t)
        k_parts.append(np.full(m, key, dtype=np.int64))
    ts = np.concatenate(ts_parts)
    keys = np.concatenate(k_parts)
    o = np.argsort(ts, kind="stable")
    ts, keys = ts[o], keys[o]

    p = SchemaProvider()
    nb = int(rng.integers(1, 5))
    bounds = np.linspace(0, len(ts), nb + 1).astype(int)
    p.add_memory_table("t", {"k": "i"}, [
        Batch(ts[a:b], {"k": keys[a:b]})
        for a, b in zip(bounds[:-1], bounds[1:]) if b > a])
    clear_sink("results")
    LocalRunner(plan_sql(f"""
        SELECT k, count(*) as cnt,
               SESSION(INTERVAL '{gap_s}' SECOND) as window
        FROM t GROUP BY 1, 3
    """, p)).run()
    out = Batch.concat(sink_output("results"))

    # oracle: the reference's incremental merge + clamp, per event
    def sessions_of(times):
        sess = []  # (start, end) clamped
        for t in times:
            placed = False
            for i, (s, e) in enumerate(sess):
                if s - gap <= t < e:
                    ns, ne = min(s, t), max(e, t + gap)
                    if ne - ns > MAX:
                        ne = ns + MAX
                    sess[i] = (ns, ne)
                    placed = True
                    break
            if not placed:
                sess.append((t, t + gap))
            sess.sort()
            merged = []
            for s, e in sess:
                if merged and s <= merged[-1][1]:
                    ps, pe = merged[-1]
                    ne = max(pe, e)
                    if ne - ps > MAX:
                        ne = ps + MAX
                    merged[-1] = (ps, ne)
                else:
                    merged.append((s, e))
            sess = merged
        return sess

    exp = collections.Counter()
    for key in range(nkeys):
        times = np.sort(ts[keys == key]).tolist()
        for (s, e) in sessions_of(times):
            cnt = sum(1 for t in times if s <= t < e)
            if cnt:
                exp[(key, s, cnt)] += 1
    got = collections.Counter(
        (int(out.columns["k"][j]), int(out.columns["window_start"][j]),
         int(out.columns["cnt"][j])) for j in range(len(out)))
    assert got == exp, (
        f"seed {seed}: missing {sorted((exp - got).keys())[:4]}, "
        f"extra {sorted((got - exp).keys())[:4]}")


@pytest.mark.parametrize("seed", [61, 62, 63, 64, 65, 66])
def test_fuzz_common_subplan_elimination(seed, monkeypatch):
    """Random q5-SHAPED self-join-on-window-aggregate queries: the
    duplicated inner aggregate must merge into one chain (the pass's
    whole point) and the merged plan's rows must equal the unmerged
    plan's rows exactly — across agg kinds, window shapes, parallelism,
    and batch splits."""
    import os

    from arroyo_tpu.sql.planner import Planner

    rng = np.random.default_rng(seed)
    n = int(rng.integers(1000, 5000))
    hop = bool(rng.integers(0, 2))
    width_s = int(rng.choice([2, 3, 4]))
    # slide must divide width (bin-path invariant, as in the reference)
    slide_s = (int(rng.choice([d for d in (1, 2) if width_s % d == 0]))
               if hop else width_s)
    nkeys = int(rng.integers(3, 30))
    par = int(rng.integers(1, 4))
    inner = rng.choice(["count(*)", "sum(v)", "max(v)"])
    outer = rng.choice(["max", "min"])
    nbatch = int(rng.integers(1, 6))
    ts = np.sort(rng.integers(0, 9 * SEC, n)).astype(np.int64)
    k = rng.integers(0, nkeys, n).astype(np.int64)
    v = rng.integers(1, 50, n).astype(np.int64)
    bounds = np.linspace(0, n, nbatch + 1).astype(int)
    win = (f"HOP(INTERVAL '{slide_s}' SECOND, INTERVAL '{width_s}' SECOND)"
           if hop else f"TUMBLE(INTERVAL '{width_s}' SECOND)")
    sql = f"""
        WITH ev AS (SELECT k AS k, v AS v FROM events)
        SELECT A.k AS k, A.num AS num
        FROM (
          SELECT T1.k, {win} AS window, {inner} AS num
          FROM ev T1 GROUP BY 1, 2
        ) AS A
        JOIN (
          SELECT {outer}(num) AS mx, window FROM (
            SELECT {inner} AS num, {win} AS window
            FROM ev T2 GROUP BY T2.k, 2
          ) AS B0 GROUP BY 2
        ) AS B
        ON A.num = B.mx AND A.window = B.window
    """

    def run():
        provider = SchemaProvider()
        provider.add_memory_table("events", {"k": "i", "v": "i"}, [
            Batch(ts[a:b], {"k": k[a:b], "v": v[a:b]})
            for a, b in zip(bounds[:-1], bounds[1:]) if b > a])
        clear_sink("results")
        prog = Planner(provider).plan(sql, query_parallelism=par)
        n_aggs = sum(1 for nd in prog.graph.nodes
                     if "window_aggregator" in nd
                     and "non_window" not in nd)
        LocalRunner(prog).run()
        rows = []
        for b in sink_output("results"):
            for i in range(len(next(iter(b.columns.values())))):
                rows.append((int(b.columns["k"][i]),
                             int(b.columns["num"][i])))
        return n_aggs, sorted(rows)

    # pin the CSE-specific shape: the argmax fusion would otherwise
    # rewrite these self-joins entirely (it has its own fuzz family)
    monkeypatch.setenv("ARROYO_ARGMAX", "0")
    monkeypatch.delenv("ARROYO_CSE", raising=False)
    merged_aggs, merged = run()
    assert merged_aggs == 1, (seed, "inner aggregate did not merge")
    monkeypatch.setenv("ARROYO_CSE", "0")
    dup_aggs, unmerged = run()
    assert dup_aggs == 2, seed
    assert merged == unmerged, (seed, len(merged), len(unmerged))
    assert len(merged) > 0, seed


@pytest.mark.parametrize("seed", [71, 72, 73, 74, 75, 76])
def test_fuzz_window_argmax_fusion(seed, monkeypatch):
    """Random q5-shaped self-joins on a window aggregate: the argmax
    fusion must replace the whole join subplan with a WindowArgmax
    operator (no window_join, ONE aggregate) and emit exactly the rows
    the unfused join emits — across inner agg kinds, outer max/min,
    window shapes, parallelism, batch splits, and tie multiplicity."""
    from arroyo_tpu.sql.planner import Planner

    rng = np.random.default_rng(seed)
    n = int(rng.integers(1000, 5000))
    hop = bool(rng.integers(0, 2))
    width_s = int(rng.choice([2, 3, 4]))
    slide_s = (int(rng.choice([d for d in (1, 2) if width_s % d == 0]))
               if hop else width_s)
    nkeys = int(rng.integers(3, 30))
    par = int(rng.integers(1, 4))
    inner = rng.choice(["count(*)", "sum(v)", "max(v)"])
    outer = rng.choice(["max", "min"])
    nbatch = int(rng.integers(1, 6))
    ts = np.sort(rng.integers(0, 9 * SEC, n)).astype(np.int64)
    k = rng.integers(0, nkeys, n).astype(np.int64)
    # small value range -> plenty of cross-key ties at the window max;
    # a null fraction makes some (key, window) aggregates SQL NULL —
    # NULL never equals the max, and must not poison the extremum
    # (an all-NaN pane once dropped the whole window's rows)
    v = rng.integers(1, 8, n).astype(np.float64)
    v[rng.random(n) < 0.15] = np.nan
    bounds = np.linspace(0, n, nbatch + 1).astype(int)
    win = (f"HOP(INTERVAL '{slide_s}' SECOND, INTERVAL '{width_s}' SECOND)"
           if hop else f"TUMBLE(INTERVAL '{width_s}' SECOND)")
    sql = f"""
        WITH ev AS (SELECT k AS k, v AS v FROM events)
        SELECT A.k AS k, A.num AS num, B.mx AS mx
        FROM (
          SELECT T1.k, {win} AS window, {inner} AS num
          FROM ev T1 GROUP BY 1, 2
        ) AS A
        JOIN (
          SELECT {outer}(num) AS mx, window FROM (
            SELECT {inner} AS num, {win} AS window
            FROM ev T2 GROUP BY T2.k, 2
          ) AS B0 GROUP BY 2
        ) AS B
        ON A.num = B.mx AND A.window = B.window
    """

    def run():
        provider = SchemaProvider()
        provider.add_memory_table("events", {"k": "i", "v": "f"}, [
            Batch(ts[a:b], {"k": k[a:b], "v": v[a:b]})
            for a, b in zip(bounds[:-1], bounds[1:]) if b > a])
        clear_sink("results")
        prog = Planner(provider).plan(sql, query_parallelism=par)
        shapes = {"join": sum(1 for nd in prog.graph.nodes
                              if "window_join" in nd),
                  "argmax": sum(1 for nd in prog.graph.nodes
                                if "window_argmax" in nd),
                  "aggs": sum(1 for nd in prog.graph.nodes
                              if "window_aggregator" in nd
                              and "non_window" not in nd)}
        LocalRunner(prog).run()
        rows = []
        for b in sink_output("results"):
            for i in range(len(next(iter(b.columns.values())))):
                rows.append((int(b.columns["k"][i]),
                             int(b.columns["num"][i]),
                             int(b.columns["mx"][i])))
        return shapes, sorted(rows)

    monkeypatch.delenv("ARROYO_ARGMAX", raising=False)
    fshape, fused = run()
    assert fshape == {"join": 0, "argmax": 1, "aggs": 1}, (seed, fshape)
    monkeypatch.setenv("ARROYO_ARGMAX", "0")
    ushape, unfused = run()
    assert ushape["join"] == 1 and ushape["argmax"] == 0, (seed, ushape)
    assert fused == unfused, (seed, len(fused), len(unfused))
    assert len(fused) > 0, seed
    # the synthesized mx column really is the join's: mx == num everywhere
    assert all(num == mx for _, num, mx in fused), seed


@pytest.mark.parametrize("seed", [91, 92, 93, 94, 95, 96])
def test_fuzz_raw_argmax_fusion(seed, monkeypatch):
    """Random q7-shaped raw-stream joins against a per-window max with a
    window-range WHERE: the raw argmax fusion (event-time provenance
    proof) must drop the whole join AND the max-side aggregate, and emit
    exactly the rows the unfused TTL-join plan emits — across window
    widths, max/min, NULL values in the maximized column, tie
    multiplicity, parallelism, and batch splits."""
    rng = np.random.default_rng(seed)
    n = int(rng.integers(1000, 5000))
    width_s = int(rng.choice([2, 3, 5]))
    par = int(rng.integers(1, 4))
    outer = rng.choice(["max", "min"])
    nbatch = int(rng.integers(1, 6))
    ts = np.sort(rng.integers(0, 11 * SEC, n)).astype(np.int64)
    a = rng.integers(0, 25, n).astype(np.int64)
    # small value range -> heavy exact-tie multiplicity; NULLs never
    # equal the extremum and must not poison it
    v = rng.integers(1, 9, n).astype(np.float64)
    v[rng.random(n) < 0.15] = np.nan
    # a late trailing slice (timestamps far behind the watermark by the
    # time it arrives): the fused plan must match these against the
    # released windows' retained final extrema exactly as the TTL join
    # still holding the max row would
    late_frac = float(rng.choice([0.0, 0.1]))
    if late_frac:
        nlate = max(int(n * late_frac), 1)
        sel = rng.permutation(n)[:nlate]
        keep = np.setdiff1d(np.arange(n), sel)
        ts = np.concatenate([ts[keep], ts[sel]])
        a = np.concatenate([a[keep], a[sel]])
        v = np.concatenate([v[keep], v[sel]])
    bounds = np.linspace(0, n, nbatch + 1).astype(int)
    sql = f"""
        SELECT B.a AS a, B.v AS v
        FROM rawbids B
        JOIN (
          SELECT {outer}(v) AS mx,
                 TUMBLE(INTERVAL '{width_s}' SECOND) AS window
          FROM rawbids GROUP BY 2
        ) AS M
        ON B.v = M.mx
        WHERE B.et >= M.window_start AND B.et < M.window_end
    """

    def run():
        provider = SchemaProvider()
        provider.add_memory_table(
            "rawbids", {"a": "i", "v": "f", "et": "t"},
            [Batch(ts[lo:hi], {"a": a[lo:hi], "v": v[lo:hi],
                               "et": ts[lo:hi].copy()})
             for lo, hi in zip(bounds[:-1], bounds[1:]) if hi > lo],
            event_time_field="et")
        clear_sink("results")
        prog = Planner(provider).plan(sql, query_parallelism=par)
        shapes = {"join": sum(1 for nd in prog.graph.nodes
                              if "join" in nd),
                  "argmax": sum(1 for nd in prog.graph.nodes
                                if "window_argmax" in nd),
                  "aggs": sum(1 for nd in prog.graph.nodes
                              if "aggregator" in nd)}
        LocalRunner(prog).run()
        rows = []
        for b in sink_output("results"):
            for i in range(len(next(iter(b.columns.values())))):
                rows.append((int(b.columns["a"][i]),
                             float(b.columns["v"][i])))
        return shapes, sorted(rows)

    from arroyo_tpu.sql.planner import Planner

    monkeypatch.delenv("ARROYO_ARGMAX", raising=False)
    fshape, fused = run()
    assert fshape == {"join": 0, "argmax": 1, "aggs": 0}, (seed, fshape)
    monkeypatch.setenv("ARROYO_ARGMAX", "0")
    ushape, unfused = run()
    assert ushape["join"] >= 1 and ushape["argmax"] == 0, (seed, ushape)
    assert fused == unfused, (seed, len(fused), len(unfused))
    assert len(fused) > 0, seed
    if late_frac == 0.0:
        # every emitted row achieves its window's extremum in the numpy
        # oracle (with late rows, which rows the watermark drops from
        # the aggregate depends on batch boundaries — the differential
        # fused==unfused assertion above is the oracle there)
        ends = (ts // (width_s * SEC) + 1) * (width_s * SEC)
        best = {}
        for e, val in zip(ends.tolist(), v.tolist()):
            if np.isnan(val):
                continue
            cur = best.get(e)
            best[e] = (val if cur is None
                       else (max(cur, val) if outer == "max"
                             else min(cur, val)))
        exp = sorted((int(ai), float(vi))
                     for ai, vi, e in zip(a.tolist(), v.tolist(),
                                          ends.tolist())
                     if not np.isnan(vi) and vi == best.get(e))
        assert fused == exp, (seed, len(fused), len(exp))


@pytest.mark.parametrize("seed", [85, 86])
def test_fuzz_raw_argmax_checkpoint_restore(seed, tmp_path):
    """Crash/restore through the RAW argmax plan (q7's fused shape):
    the candidate buffer, its timers, the released-window guard, and
    the persisted final-extrema table must round-trip so the restored
    run emits exactly what an uncrashed run of the same program does."""
    import asyncio
    import json as _json

    from arroyo_tpu.engine.engine import Engine
    from arroyo_tpu.sql.planner import Planner
    from arroyo_tpu.types import StopMode

    rng = np.random.default_rng(seed)
    total = int(rng.integers(40000, 70000))
    crash_after = float(rng.uniform(0.05, 0.25))
    url = f"file://{tmp_path}/ckpt"

    def sql(out_path):
        # price % 97 gives heavy tie multiplicity at each window max
        return f"""
        CREATE TABLE nexmark WITH (connector = 'nexmark',
          event_rate = '20000', num_events = '{total}',
          batch_size = '2048', rate_limited = 'false',
          base_time_micros = '1700000000000000');
        CREATE TABLE outj (auction BIGINT, p BIGINT) WITH (
          connector = 'single_file', path = '{out_path}', type = 'sink');
        INSERT INTO outj
        WITH bids AS (SELECT bid.auction AS auction,
                             bid.price % 97 AS p,
                             bid.datetime AS et
            FROM nexmark WHERE bid IS NOT NULL)
        SELECT B.auction AS auction, B.p AS p
        FROM bids B
        JOIN (
          SELECT max(p) AS mx, TUMBLE(INTERVAL '1' SECOND) AS window
          FROM bids GROUP BY 2
        ) AS M ON B.p = M.mx
        WHERE B.et >= M.window_start AND B.et < M.window_end
        """

    def plan(out_path):
        prog = Planner(SchemaProvider()).plan(sql(out_path))
        assert any("window_argmax" in n for n in prog.graph.nodes)
        assert not any("join" in n for n in prog.graph.nodes)
        return prog

    oracle_path = f"{tmp_path}/oracle.jsonl"
    crash_path = f"{tmp_path}/crash.jsonl"

    async def run_plain():
        await Engine.for_local(plan(oracle_path),
                               f"rawam-oracle-{seed}").start().join()

    async def run_with_crash():
        eng = Engine.for_local(plan(crash_path), f"rawam-{seed}",
                               checkpoint_url=url)
        running = eng.start()
        join_t = asyncio.ensure_future(running.join())
        await asyncio.sleep(crash_after)
        if join_t.done():
            return False
        await running.checkpoint(1)
        ok = await running.wait_for_checkpoint(1)
        if not ok or join_t.done():
            await asyncio.wait([join_t])
            return False
        await running.stop(StopMode.IMMEDIATE)
        try:
            await join_t
        except RuntimeError:
            pass
        return True

    async def run_restored():
        eng = Engine.for_local(plan(crash_path), f"rawam-{seed}",
                               checkpoint_url=url, restore_epoch=1)
        await eng.start().join()

    asyncio.run(run_plain())
    if asyncio.run(run_with_crash()):
        asyncio.run(run_restored())
    exp = sorted((r["auction"], r["p"]) for r in
                 (_json.loads(line) for line in open(oracle_path)))
    got = sorted((r["auction"], r["p"]) for r in
                 (_json.loads(line) for line in open(crash_path)))
    assert got == exp, (seed, len(got), len(exp))
    assert len(exp) > 0, seed


@pytest.mark.parametrize("seed", [81, 82, 83])
def test_fuzz_argmax_fusion_checkpoint_restore(seed, tmp_path):
    """Crash/restore through the FUSED argmax plan: the WindowArgmax
    buffer and its timers must round-trip state so the restored run
    still emits exactly the unfused join's rows."""
    import asyncio
    import json as _json

    from arroyo_tpu.engine.engine import Engine
    from arroyo_tpu.sql.planner import Planner
    from arroyo_tpu.types import StopMode

    rng = np.random.default_rng(seed)
    total = int(rng.integers(3000, 6000))
    crash_after = float(rng.uniform(0.05, 0.2))
    out_path = f"{tmp_path}/out.jsonl"
    url = f"file://{tmp_path}/ckpt"
    job = f"argmax-restore-{seed}"
    sql = f"""
    CREATE TABLE imp WITH (connector = 'impulse', event_rate = '30000',
      message_count = '{total}', batch_size = '128',
      event_time_interval_micros = '1000',
      base_time_micros = '1700000000000000');
    CREATE TABLE outj (k BIGINT, num BIGINT) WITH (
      connector = 'single_file', path = '{out_path}', type = 'sink');
    INSERT INTO outj
    SELECT A.k AS k, A.num AS num
    FROM (
      SELECT counter % 7 AS k, TUMBLE(INTERVAL '1' SECOND) AS window,
             count(*) AS num
      FROM imp GROUP BY 1, 2
    ) AS A
    JOIN (
      SELECT max(num) AS mx, window FROM (
        SELECT count(*) AS num, counter % 7 AS k,
               TUMBLE(INTERVAL '1' SECOND) AS window
        FROM imp GROUP BY 2, 3
      ) AS B0 GROUP BY 2
    ) AS B ON A.num = B.mx AND A.window = B.window
    """

    def plan():
        prog = Planner(SchemaProvider()).plan(sql)
        assert any("window_argmax" in n for n in prog.graph.nodes)
        return prog

    async def run_with_crash():
        eng = Engine.for_local(plan(), job, checkpoint_url=url)
        running = eng.start()
        join_t = asyncio.ensure_future(running.join())
        await asyncio.sleep(crash_after)
        if join_t.done():
            return False
        await running.checkpoint(1)
        ok = await running.wait_for_checkpoint(1)
        if not ok or join_t.done():
            await asyncio.wait([join_t])
            return False
        await running.stop(StopMode.IMMEDIATE)
        try:
            await join_t
        except RuntimeError:
            pass
        return True

    async def run_restored():
        eng = Engine.for_local(plan(), job, checkpoint_url=url,
                               restore_epoch=1)
        await eng.start().join()

    if asyncio.run(run_with_crash()):
        asyncio.run(run_restored())
    got = sorted((r["k"], r["num"]) for r in
                 (_json.loads(line) for line in open(out_path)))

    # oracle: per tumbling second, the keys achieving the max count
    counters = np.arange(total, dtype=np.int64)
    ts = 1_700_000_000_000_000 + counters * 1000
    k = counters % 7
    wend = (ts // SEC + 1) * SEC
    exp = []
    for w in np.unique(wend):
        sel = wend == w
        ks, cnts = np.unique(k[sel], return_counts=True)
        mx = cnts.max()
        exp.extend((int(kk), int(mx)) for kk in ks[cnts == mx])
    assert got == sorted(exp), (seed, len(got), len(exp))
