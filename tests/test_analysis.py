"""arroyolint contract suite: each pass catches its seeded bug class
(including a reintroduction of the round-5 Nexmark 3-vs-4 unpack bug),
waivers and the baseline suppress correctly, the proto-drift check
matches the real repo, and the plan validator accepts real plans while
rejecting mutated ones."""

import ast
import json
import subprocess
import sys
import textwrap

import pytest

from arroyo_tpu.analysis import core
from arroyo_tpu.analysis import (
    async_blocking,
    checkpoint_arity,
    host_sync,
    proto_drift,
    trace_purity,
)


def _run_pass(mod, src, path="fixture.py", **kw):
    src = textwrap.dedent(src)
    return mod.check(ast.parse(src), src.splitlines(), path, **kw)


# ---------------------------------------------------------------------------
# checkpoint arity — the round-5 Nexmark bug class
# ---------------------------------------------------------------------------

ROUND5_NEXMARK_BUG = """
    import asyncio

    class Src:
        async def run(self, ctx):
            state = ctx.state.get_global_keyed_state("s")
            saved = state.get(0)
            loop = asyncio.get_event_loop()

            def gen_next():
                b, nums = gen.next_batch(64)
                return b, nums, gen.events_so_far, gen.snapshot_rng_state()

            fut = loop.run_in_executor(None, gen_next) if gen.has_next \\
                else None
            while fut is not None:
                batch, nums, count_after = await fut
                fut = (loop.run_in_executor(None, gen_next)
                       if gen.has_next else None)
                state.insert(0, (batch, nums, count_after, "rng_snap"))
"""


def test_ckpt_arity_catches_round5_nexmark_bug():
    findings = _run_pass(checkpoint_arity, ROUND5_NEXMARK_BUG)
    codes = {f.code for f in findings}
    # the consumer unpacks 3 values from the 4-tuple-returning producer
    # routed through run_in_executor — exactly the round-5 crash
    assert "tuple-unpack-mismatch" in codes, findings
    assert any("gen_next" in f.message for f in findings)


def test_ckpt_arity_cli_exits_nonzero_on_seeded_bug(tmp_path):
    """Acceptance: the analyzer CLI exits non-zero on the seeded
    round-5 fixture (and test_cli_repo_is_green covers exit 0)."""
    fixture = tmp_path / "nexmark_round5.py"
    fixture.write_text(textwrap.dedent(ROUND5_NEXMARK_BUG))
    r = subprocess.run(
        [sys.executable, "-m", "arroyo_tpu.analysis", "--no-baseline",
         str(fixture)], capture_output=True, text=True)
    assert r.returncode != 0, r.stdout + r.stderr
    assert "tuple-unpack-mismatch" in r.stdout


def test_ckpt_arity_clean_on_fixed_shape():
    src = ROUND5_NEXMARK_BUG.replace(
        "batch, nums, count_after = await fut",
        "batch, nums, count_after, rng_snap = await fut")
    assert not _run_pass(checkpoint_arity, src)


def test_ckpt_arity_state_unpack_mismatch():
    findings = _run_pass(checkpoint_arity, """
        async def run(ctx):
            state = ctx.state.get_global_keyed_state("s")
            saved = state.get(0)
            if saved is not None:
                base_time, split, count = saved
            state.insert(0, (1, 2, 3, 4))
    """)
    assert [f.code for f in findings] == ["state-unpack-mismatch"]


def test_ckpt_arity_slice_and_index_overrun():
    findings = _run_pass(checkpoint_arity, """
        def f(ctx):
            state = ctx.state.get_global_keyed_state("s")
            saved = state.get(0)
            a = saved[:4]
            b = saved[3]
            state.insert(0, (1, 2, 3))
    """)
    codes = sorted(f.code for f in findings)
    assert codes == ["state-index-overrun", "state-slice-overrun"]


def test_ckpt_arity_nested_helper_does_not_contaminate_outer():
    """A nested helper's tuple returns must not leak into the enclosing
    function's arity set: outer() returns a 2-tuple even though its
    nested helper returns 4 — unpacking 4 from outer() is the bug."""
    findings = _run_pass(checkpoint_arity, """
        async def outer():
            def helper():
                return 1, 2, 3, 4
            return 1, 2

        async def consume():
            a, b, c, d = await outer()
            w, x, y = helper()
    """)
    msgs = sorted(f.message for f in findings)
    assert len(findings) == 2, findings
    assert "unpacking 4 values from outer()" in msgs[1]
    assert "unpacking 3 values from helper()" in msgs[0]


def test_ckpt_arity_guarded_access_ok():
    """The real nexmark shape: slice within arity, guarded index."""
    findings = _run_pass(checkpoint_arity, """
        def f(ctx):
            state = ctx.state.get_global_keyed_state("s")
            saved = state.get(0)
            base, split, count = saved[:3]
            rng = saved[3] if len(saved) > 3 else None
            state.insert(0, (base, split, count, rng))
    """)
    assert not findings


# ---------------------------------------------------------------------------
# blocking calls in async
# ---------------------------------------------------------------------------


def test_async_blocking_flags_sleep_and_result():
    findings = _run_pass(async_blocking, """
        import time

        async def poll():
            time.sleep(1)
            fut.result()
            open("/tmp/x")
    """)
    assert sorted(f.code for f in findings) == [
        "future-result", "sleep", "sync-io"]


def test_async_blocking_ignores_sync_and_nested_executor_helpers():
    findings = _run_pass(async_blocking, """
        import time

        def sync_retry():
            time.sleep(1)  # sync helper: runs on an executor

        async def poll():
            def offloaded():
                time.sleep(2)  # shipped to run_in_executor
            await loop.run_in_executor(None, offloaded)
            await asyncio.sleep(0)
    """)
    assert not findings


def test_async_blocking_waiver_suppresses():
    src = textwrap.dedent("""
        import time

        async def poll():
            time.sleep(1)  # arroyolint: disable=async-blocking -- test fixture
    """)
    findings = _run_pass(async_blocking, src)
    waivers, problems = core.parse_waivers(src.splitlines(), "fixture.py")
    core.apply_waivers(findings, waivers)
    assert not problems
    assert len(findings) == 1 and findings[0].waived


def test_waiver_without_reason_is_itself_a_finding():
    src = "x = 1  # arroyolint: disable=host-sync\n"
    _, problems = core.parse_waivers(src.splitlines(), "fixture.py")
    assert [p.code for p in problems] == ["missing-reason"]


def test_reasonless_disable_all_cannot_self_waive(tmp_path):
    """A reasonless `disable=all` must NOT waive its own missing-reason
    enforcement finding — the gate stays red, and --write-baseline
    refuses to accept the enforcement finding."""
    fixture = tmp_path / "fx.py"
    fixture.write_text(textwrap.dedent("""
        import time

        async def poll():
            time.sleep(1)  # arroyolint: disable=all
    """))
    findings = core.run_analysis([str(fixture)], baseline_path=None)
    gate = core.unwaived(findings)
    assert [f.code for f in gate] == ["missing-reason"], findings
    baseline = tmp_path / "b.json"
    core.write_baseline(findings, str(baseline))
    again = core.run_analysis([str(fixture)],
                              baseline_path=str(baseline))
    assert [f.code for f in core.unwaived(again)] == ["missing-reason"]


# ---------------------------------------------------------------------------
# host-device sync
# ---------------------------------------------------------------------------


def test_host_sync_flags_readbacks_in_scope():
    findings = _run_pass(host_sync, """
        import numpy as np

        def process_batch(dev):
            host = np.asarray(dev)
            n = dev.sum().item()
            dev.block_until_ready()
    """, path="arroyo_tpu/ops/fake.py")
    assert sorted(f.code for f in findings) == [
        "asarray", "block-until-ready", "item"]


def test_host_sync_checkpoint_paths_exempt_and_scope_enforced():
    src = """
        import numpy as np

        def snapshot_state(dev):
            return np.asarray(dev)  # checkpoint path: intended readback
    """
    assert not _run_pass(host_sync, src, path="arroyo_tpu/ops/fake.py")
    # connectors are out of scope entirely (host-side numpy territory)
    src2 = "import numpy as np\ndef f(d):\n    return np.asarray(d)\n"
    assert not host_sync.check(ast.parse(src2), src2.splitlines(),
                               "arroyo_tpu/connectors/fake.py")
    assert host_sync.check(ast.parse(src2), src2.splitlines(),
                           "anywhere.py", force=True)


def test_host_sync_jnp_metadata_not_flagged():
    findings = _run_pass(host_sync, """
        import jax.numpy as jnp

        NEG = float(jnp.finfo(jnp.float64).min)

        def f(x):
            return float(jnp.sum(x))
    """, path="arroyo_tpu/ops/fake.py")
    assert [f.code for f in findings] == ["scalarize"]


# ---------------------------------------------------------------------------
# trace purity
# ---------------------------------------------------------------------------


def test_trace_purity_flags_impure_jit_targets():
    findings = _run_pass(trace_purity, """
        import time
        import jax

        @jax.jit
        def kernel(x):
            t = time.time()
            return x * t

        def pallas_kernel(ref):
            return np.random.random() + ref[0]

        out = pallas_call(pallas_kernel, out_shape=None)

        def pure(x):
            return x + 1

        pure_j = jax.jit(pure)
    """)
    assert sorted(f.code for f in findings) == [
        "impure-random", "wall-clock"]
    assert all("pure" not in f.message.split("(")[0] for f in findings)


def test_trace_purity_flags_global_mutation():
    findings = _run_pass(trace_purity, """
        import jax

        COUNT = 0

        @jax.jit
        def kernel(x):
            global COUNT
            COUNT += 1
            return x
    """)
    assert [f.code for f in findings] == ["global-mutation"]


# ---------------------------------------------------------------------------
# proto drift
# ---------------------------------------------------------------------------


def test_proto_drift_repo_in_sync():
    assert proto_drift.check_repo(core.REPO_ROOT) == []


def test_proto_drift_detects_tampering():
    from arroyo_tpu.rpc.gen import rpc_pb2

    with open(f"{core.REPO_ROOT}/{proto_drift.PROTO_REL}") as fh:
        messages, services = proto_drift.parse_proto(fh.read())
    # simulate descriptor-surgery drift: wrong number, wrong type,
    # missing field, phantom message
    messages["HeartbeatReq"]["time"] = (9, "uint64", "")
    messages["RegisterWorkerReq"]["slots"] = (5, "string", "")
    messages["CommitReq"]["phantom"] = (3, "bool", "")
    messages["PhantomMsg"] = {"x": (1, "string", "")}
    findings = proto_drift.compare(messages, services,
                                   rpc_pb2.DESCRIPTOR, "rpc.proto")
    codes = {f.code for f in findings}
    assert codes == {"field-number", "field-type", "missing-field",
                     "missing-message"}, findings


def test_proto_drift_parser_reads_real_schema():
    with open(f"{core.REPO_ROOT}/{proto_drift.PROTO_REL}") as fh:
        messages, services = proto_drift.parse_proto(fh.read())
    assert messages["HeartbeatReq"]["metrics"] == (4, "bytes", "optional")
    assert messages["StartExecutionReq"]["worker_data_addrs"] == (
        5, "map<string,string>", "")
    assert messages["StartExecutionReq"]["tasks"] == (
        3, "TaskAssignment", "repeated")
    assert services["ControllerGrpc"]["Heartbeat"] == (
        "HeartbeatReq", "Empty")


# ---------------------------------------------------------------------------
# baseline + end-to-end runner
# ---------------------------------------------------------------------------


def test_baseline_roundtrip(tmp_path):
    fixture = tmp_path / "fx.py"
    fixture.write_text(textwrap.dedent("""
        import time

        async def poll():
            time.sleep(1)
    """))
    baseline = tmp_path / "baseline.json"
    first = core.run_analysis([str(fixture)], baseline_path=None)
    assert core.unwaived(first)
    core.write_baseline(first, str(baseline), reason="test accepts")
    again = core.run_analysis([str(fixture)],
                              baseline_path=str(baseline))
    assert not core.unwaived(again)
    assert any(f.baselined for f in again)
    # a NEW finding is not masked by the baseline
    fixture.write_text(fixture.read_text()
                       + "\nasync def poll2():\n    time.sleep(2)\n")
    third = core.run_analysis([str(fixture)],
                              baseline_path=str(baseline))
    fresh = core.unwaived(third)
    assert len(fresh) == 1 and fresh[0].line > 5


def test_fingerprints_stable_across_line_drift(tmp_path):
    fixture = tmp_path / "fx.py"
    body = "import time\n\nasync def poll():\n    time.sleep(1)\n"
    fixture.write_text(body)
    f1 = core.run_analysis([str(fixture)], baseline_path=None)
    fixture.write_text("# a new leading comment\n# another\n" + body)
    f2 = core.run_analysis([str(fixture)], baseline_path=None)
    fp = lambda fs: {f.fingerprint for f in fs
                     if f.pass_id == "async-blocking"}
    assert fp(f1) == fp(f2)


@pytest.mark.slow
def test_cli_repo_is_green():
    """Acceptance: `python -m arroyo_tpu.analysis` exits 0 on the repo
    (zero unwaived findings against the checked-in baseline)."""
    r = subprocess.run([sys.executable, "-m", "arroyo_tpu.analysis"],
                       capture_output=True, text=True,
                       cwd=core.REPO_ROOT)
    assert r.returncode == 0, r.stdout + r.stderr


# ---------------------------------------------------------------------------
# plan validator (unit level; fuzz-plan routing lives in test_fuzz_sql)
# ---------------------------------------------------------------------------


def _simple_windowed_program(parallelism=2):
    from arroyo_tpu.graph.logical import AggKind, AggSpec, Stream

    return (Stream.source("impulse", {"event_rate": 1000.0,
                                      "message_count": 10},
                          parallelism=parallelism)
            .watermark()
            .key_by("counter")
            .tumbling_aggregate(1_000_000,
                                [AggSpec(AggKind.COUNT, None, "c")])
            .sink("blackhole"))


def test_plan_validator_accepts_stream_api_program():
    from arroyo_tpu.analysis.plan_validator import (
        errors_of,
        validate_program,
    )

    assert not errors_of(validate_program(_simple_windowed_program()))


def test_plan_validator_rejects_forward_into_keyed_state():
    from arroyo_tpu.analysis.plan_validator import (
        PlanValidationError,
        check_program,
    )
    from arroyo_tpu.graph.logical import EdgeType

    prog = _simple_windowed_program()
    for _, dst, data in prog.graph.edges(data=True):
        if data["edge"].typ is EdgeType.SHUFFLE:
            data["edge"].typ = EdgeType.FORWARD
    with pytest.raises(PlanValidationError) as ei:
        check_program(prog)
    assert any(d.code == "keyed-not-shuffled"
               for d in ei.value.diagnostics)


def test_plan_validator_exempts_pinned_merge_stage():
    """The global TopN merge stage is FORWARD-fed by design: one pinned
    subtask sees everything, so no shuffle is required."""
    from arroyo_tpu.analysis.plan_validator import (
        errors_of,
        validate_program,
    )
    from arroyo_tpu.graph.logical import EdgeType

    prog = _simple_windowed_program()
    for _, dst, data in prog.graph.edges(data=True):
        if data["edge"].typ is EdgeType.SHUFFLE:
            data["edge"].typ = EdgeType.FORWARD
            prog.node(dst).max_parallelism = 1
    assert not errors_of(validate_program(prog))


def test_plan_validator_rejects_missing_watermark():
    from arroyo_tpu.analysis.plan_validator import (
        errors_of,
        validate_program,
    )
    from arroyo_tpu.graph.logical import AggKind, AggSpec, Stream

    prog = (Stream.source("impulse", {"event_rate": 1000.0,
                                      "message_count": 10})
            .key_by("counter")
            .tumbling_aggregate(1_000_000,
                                [AggSpec(AggKind.COUNT, None, "c")])
            .sink("blackhole"))
    errs = errors_of(validate_program(prog))
    assert any(d.code == "window-no-watermark" for d in errs)


def test_plan_validator_rejects_cycle_and_bad_spec():
    from arroyo_tpu.analysis.plan_validator import (
        errors_of,
        validate_program,
    )
    from arroyo_tpu.graph.logical import EdgeType

    prog = _simple_windowed_program()
    nodes = list(prog.graph.nodes)
    prog.add_edge(nodes[-1], nodes[0], EdgeType.FORWARD)
    errs = errors_of(validate_program(prog))
    assert [d.code for d in errs] == ["cycle"]


def test_plan_validator_warns_on_dead_end_and_slide():
    from arroyo_tpu.analysis.plan_validator import (
        errors_of,
        validate_program,
    )
    from arroyo_tpu.graph.logical import AggKind, AggSpec, Stream

    s = (Stream.source("impulse", {"event_rate": 1000.0,
                                   "message_count": 10})
         .watermark()
         .key_by("counter")
         .sliding_aggregate(3_000_000, 2_000_000,
                            [AggSpec(AggKind.COUNT, None, "c")]))
    prog = s.program  # no sink: dead end
    diags = validate_program(prog)
    assert not errors_of(diags)
    codes = {d.code for d in diags}
    assert {"dead-end", "slide-width"} <= codes


def test_rest_validate_endpoint_reports_diagnostics(run_async):
    """The console's validation endpoint carries the structured plan
    diagnostics for a valid windowed query (no error severity)."""
    import httpx

    from arroyo_tpu.api.rest import ApiServer
    from arroyo_tpu.controller.controller import ControllerServer

    async def scenario():
        controller = ControllerServer()
        await controller.start()
        api = ApiServer(controller)
        port = await api.start()
        try:
            async with httpx.AsyncClient(
                    base_url=f"http://127.0.0.1:{port}",
                    timeout=30) as c:
                r = await c.post("/v1/pipelines/validate", json={
                    "query": "CREATE TABLE imp WITH "
                             "(connector='impulse', event_rate='100', "
                             "message_count='10');"
                             "SELECT count(*) as c, "
                             "TUMBLE(INTERVAL '1' SECOND) as w "
                             "FROM imp GROUP BY 2"})
                assert r.status_code == 200, r.text
                out = r.json()
                assert "diagnostics" in out
                assert not [d for d in out["diagnostics"]
                            if d["severity"] == "error"], out
        finally:
            await api.stop()
            await controller.stop()

    run_async(scenario())
