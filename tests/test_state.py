"""State backend tests against a real ParquetBackend writing to a local
directory — the reference's pattern (arroyo-state/src/lib.rs:354-682):
checkpoint -> restore round-trips per table type, key-range-filtered restore
(rescaling), delete tombstones, epoch cleanup, and a full pipeline
crash/restore with exactly-once output."""

import asyncio
import json
import uuid

import numpy as np
import pytest

from arroyo_tpu import AggKind, AggSpec, Batch, Stream
from arroyo_tpu.connectors.memory import clear_sink, sink_output
from arroyo_tpu.engine.engine import Engine
from arroyo_tpu.state.backend import ParquetBackend, TableSnapshot
from arroyo_tpu.state.store import StateStore
from arroyo_tpu.state.tables import TableDescriptor, TableType
from arroyo_tpu.types import StopMode, TaskInfo

SEC = 1_000_000


@pytest.fixture
def backend(tmp_path):
    return ParquetBackend.for_url(f"file://{tmp_path}")


def fresh_task(parallelism=1, idx=0):
    return TaskInfo(f"job-{uuid.uuid4().hex[:8]}", "op-1", "test", idx,
                    parallelism)


def test_kv_tables_roundtrip(backend):
    task = fresh_task()
    store = StateStore(task, backend)
    g = store.get_global_keyed_state("g")
    g.insert("offset", 42)
    k = store.get_keyed_state("k")
    k.insert(100, 7, {"a": 1})
    tkm = store.get_time_key_map("t")
    tkm.insert(10, "x", 1.5)
    tkm.insert(20, "y", 2.5)
    ktm = store.get_key_time_multi_map("m")
    ktm.insert(10, 5, "v1")
    ktm.insert(11, 5, "v2")
    store.checkpoint(1, watermark=12345)

    store2 = StateStore(task, backend, restore_epoch=1)
    assert store2.restore_watermark() == 12345
    assert store2.get_global_keyed_state("g").get("offset") == 42
    assert store2.get_keyed_state("k").get(7) == {"a": 1}
    assert store2.get_time_key_map("t").get(20, "y") == 2.5
    assert store2.get_key_time_multi_map("m").get_time_range(5, 0, 100) == \
        ["v1", "v2"]


def test_global_state_stale_peer_copy_never_wins(backend):
    """The second-generation restore bug (regression): global tables
    merge across EVERY subtask's files, and a restored subtask
    re-persists its peers' entries it merely read — so epoch 2's file
    for subtask 0 holds a STALE COPY of subtask 1's source offset.
    Un-versioned restore resolved that collision by file order; a
    source could then resume from the stale offset and replay
    already-delivered events.  Entry versions pin newest-wins."""
    job = f"job-{uuid.uuid4().hex[:8]}"
    t0 = TaskInfo(job, "src", "src", 0, 2)
    t1 = TaskInfo(job, "src", "src", 1, 2)

    # epoch 1: each subtask records only its own offset
    s0 = StateStore(t0, backend)
    s0.get_global_keyed_state("s").insert(0, 100)
    s0.checkpoint(1, watermark=None)
    s1 = StateStore(t1, backend)
    s1.get_global_keyed_state("s").insert(1, 100)
    s1.checkpoint(1, watermark=None)

    # restore -> subtask 0 now ALSO holds subtask 1's entry (stale once
    # subtask 1 advances); both advance their OWN key and checkpoint 2
    r0 = StateStore(t0, backend, restore_epoch=1)
    g0 = r0.get_global_keyed_state("s")
    assert g0.get(1) == 100  # the merged peer copy
    g0.insert(0, 200)
    r1 = StateStore(t1, backend, restore_epoch=1)
    g1 = r1.get_global_keyed_state("s")
    g1.insert(1, 250)
    r0.checkpoint(2, watermark=None)
    r1.checkpoint(2, watermark=None)

    # epoch-2 restore: every subtask must see every key's NEWEST value,
    # whatever file order the merge read them in
    for t in (t0, t1):
        g = StateStore(t, backend,
                       restore_epoch=2).get_global_keyed_state("s")
        assert g.get(0) == 200 and g.get(1) == 250, (t.task_index,
                                                     g.get_all())


def test_batch_buffer_roundtrip(backend):
    task = fresh_task()
    store = StateStore(task, backend)
    buf = store.get_batch_buffer("b")
    b = Batch(np.array([1, 2, 3], dtype=np.int64),
              {"k": np.array([10, 20, 30], dtype=np.int64),
               "s": np.array(["a", "b", "c"], dtype=object)}).with_key(["k"])
    buf.append(b)
    store.checkpoint(1, None)

    store2 = StateStore(task, backend, restore_epoch=1)
    buf2 = store2.get_batch_buffer("b")
    restored = buf2.all()
    assert restored is not None and len(restored) == 3
    assert restored.key_hash is not None
    assert list(restored.columns["s"]) == ["a", "b", "c"]


def test_keyed_restore_filters_by_key_range(backend):
    """Rescale 1 -> 2: each new subtask only restores keys it owns
    (parquet.rs:194-218 semantics)."""
    task = fresh_task(parallelism=1)
    store = StateStore(task, backend)
    k = store.get_keyed_state("k")
    rng = np.random.default_rng(1)
    hashes = rng.integers(0, 1 << 63, 100, dtype=np.uint64) * 2
    for h in hashes.tolist():
        k.insert(0, int(h), h % 97)
    store.checkpoint(1, None)

    total = 0
    for idx in range(2):
        t2 = TaskInfo(task.job_id, task.operator_id, "test", idx, 2)
        s2 = StateStore(t2, backend, restore_epoch=1)
        k2 = s2.get_keyed_state("k")
        lo, hi = t2.key_range
        for key, _ in k2.items():
            assert lo <= key <= hi
        total += len(k2)
    assert total == len(set(hashes.tolist()))


def test_delete_tombstones(backend):
    task = fresh_task()
    store = StateStore(task, backend)
    k = store.get_keyed_state("k")
    k.insert(0, 1, "keep")
    k.insert(0, 2, "remove")
    store.checkpoint(1, None)
    k.remove(2)
    store.note_delete("k", 2)
    store.checkpoint(2, None)

    s2 = StateStore(task, backend, restore_epoch=2)
    k2 = s2.get_keyed_state("k")
    assert k2.get(1) == "keep"
    assert k2.get(2) is None


def test_epoch_cleanup(backend):
    task = fresh_task()
    for epoch in (1, 2, 3):
        store = StateStore(task, backend)
        store.get_global_keyed_state("g").insert("e", epoch)
        store.checkpoint(epoch, None)
    backend.cleanup_before(task.job_id, 3)
    files = backend.storage.list(f"{task.job_id}/checkpoints")
    assert files and all("checkpoint-0000003" in f for f in files)


def test_pipeline_crash_restore_exactly_once(tmp_path):
    """Full engine: run with checkpoints, 'crash', restore from the last
    epoch, and verify windowed output is exactly-once (no duplicates, no
    gaps) — the reference's smoke-test pattern."""
    url = f"file://{tmp_path}/ckpt"
    out_path = f"{tmp_path}/out.jsonl"
    job = "restore-job"
    total = 3000

    def build():
        return (Stream.source("impulse", {
                    "event_rate": 30_000.0, "message_count": total,
                    "event_time_interval_micros": 1000, "batch_size": 100})
                .watermark(max_lateness_micros=0)
                .map(lambda c: {"counter": c["counter"],
                                "bucket": c["counter"] % 7}, name="b")
                .key_by("bucket")
                .tumbling_aggregate(
                    100 * 1000, [AggSpec(AggKind.COUNT, None, "cnt"),
                                 AggSpec(AggKind.SUM, "counter", "sum_c")])
                .sink("single_file", {"path": out_path}))

    async def run_with_crash():
        eng = Engine.for_local(build(), job, checkpoint_url=url)
        running = eng.start()
        await asyncio.sleep(0.04)
        await running.checkpoint(1)
        # an epoch is restorable only once all subtasks completed it
        assert await running.wait_for_checkpoint(1)
        # crash: stop immediately without letting it finish
        await running.stop(StopMode.IMMEDIATE)
        try:
            await running.join()
        except RuntimeError:
            pass

    asyncio.run(run_with_crash())

    async def run_restored():
        eng = Engine.for_local(build(), job, checkpoint_url=url,
                               restore_epoch=1)
        running = eng.start()
        await running.join()

    asyncio.run(run_restored())

    rows = [json.loads(l) for l in open(out_path)]
    # every counter value 0..total-1 counted exactly once across windows
    assert sum(r["cnt"] for r in rows) == total
    assert sum(r["sum_c"] for r in rows) == total * (total - 1) // 2
    # no duplicate (bucket, window_end) rows
    seen = set()
    for r in rows:
        key = (r["bucket"], r["window_end"])
        assert key not in seen, f"duplicate window emission {key}"
        seen.add(key)


def test_compaction_merges_subtask_files_with_tombstones(backend):
    """compact_operator merges gen-0 per-subtask files into key-range
    partitions, applies DeleteKey tombstones, and restore prefers the
    compacted generation (parquet.rs:451-560; test_key_state_compaction,
    arroyo-state/src/lib.rs:610-681)."""
    job = f"job-{uuid.uuid4().hex[:8]}"
    # two subtasks checkpoint the same epoch
    for idx in range(2):
        task = TaskInfo(job, "op-1", "test", idx, 2)
        store = StateStore(task, backend)
        ks = store.get_keyed_state("k")
        for i in range(idx * 50, idx * 50 + 50):
            ks.insert(i, i, i * 10)
        # delete a few keys (tombstones within the epoch snapshot)
        for i in range(idx * 50, idx * 50 + 5):
            ks.remove(i)
            store.note_delete("k", i)
        store.checkpoint(1, None)

    result = backend.compact_operator(job, "op-1", 1, n_partitions=2)
    assert result["to_load"] and result["to_drop"]
    # gen-0 files are gone, marker present
    op_dir = backend.operator_dir(job, 1, "op-1")
    names = [f.rsplit("/", 1)[-1] for f in backend.storage.list(op_dir)]
    assert not any(n.startswith("table-") for n in names)
    assert "compaction.json" in names
    assert sum(1 for n in names if n.startswith("compacted-")) >= 1

    # restore at original parallelism: tombstoned keys absent, rest intact
    restored = {}
    for idx in range(2):
        task = TaskInfo(job, "op-1", "test", idx, 2)
        s2 = StateStore(task, backend, restore_epoch=1)
        restored.update(dict(s2.get_keyed_state("k").items()))
    expect = {i: i * 10 for i in range(100)
              if i not in set(range(0, 5)) | set(range(50, 55))}
    assert restored == expect

    # rescale 2 -> 3 against the compacted generation still works
    rescaled = {}
    for idx in range(3):
        task = TaskInfo(job, "op-1", "test", idx, 3)
        s3 = StateStore(task, backend, restore_epoch=1)
        part = dict(s3.get_keyed_state("k").items())
        assert not (set(rescaled) & set(part)), "key owned by two subtasks"
        rescaled.update(part)
    assert rescaled == expect


def test_compaction_preserves_batch_and_global_tables(backend):
    """__batch__ / global rows survive compaction untouched."""
    job = f"job-{uuid.uuid4().hex[:8]}"
    task = TaskInfo(job, "op-2", "test", 0, 1)
    store = StateStore(task, backend)
    g = store.get_global_keyed_state("g")
    g.insert("offset", 1234)
    buf = store.get_batch_buffer("b")
    batch = Batch(np.arange(3, dtype=np.int64),
                  {"s": np.array(["a", "b", "c"], dtype=object)})
    buf.append(batch)
    store.checkpoint(1, None)

    backend.compact_operator(job, "op-2", 1)
    s2 = StateStore(task, backend, restore_epoch=1)
    assert s2.get_global_keyed_state("g").get("offset") == 1234
    rb = s2.get_batch_buffer("b").all()
    assert rb is not None and list(rb.columns["s"]) == ["a", "b", "c"]


@pytest.mark.slow
def test_controller_compaction_cycle(tmp_path):
    """LocalRunner-style engine + manual compaction via the backend matches
    the controller path: checkpoint N epochs, compact one, restore from it."""
    url = f"file://{tmp_path}/ck"
    out = f"{tmp_path}/o.jsonl"
    job = "compact-e2e"

    def build():
        return (Stream.source("impulse", {
                    "event_rate": 50_000.0, "message_count": 100_000,
                    "event_time_interval_micros": 1000, "batch_size": 100})
                .watermark(max_lateness_micros=0)
                .map(lambda c: {"counter": c["counter"],
                                "bucket": c["counter"] % 5}, name="b")
                .key_by("bucket")
                .tumbling_aggregate(
                    50 * 1000, [AggSpec(AggKind.COUNT, None, "cnt")])
                .sink("single_file", {"path": out}))

    async def run_and_compact():
        eng = Engine.for_local(build(), job, checkpoint_url=url)
        running = eng.start()
        await asyncio.sleep(0.05)
        await running.checkpoint(1)
        assert await running.wait_for_checkpoint(1)
        backend = ParquetBackend.for_url(url)
        for op_id in {t.operator_id for t in
                      (st.task_info for st in eng.subtasks.values())}:
            backend.compact_operator(job, op_id, 1)
        await running.stop(StopMode.IMMEDIATE)
        try:
            await running.join()
        except RuntimeError:
            pass

    asyncio.run(run_and_compact())

    async def run_restored():
        eng = Engine.for_local(build(), job, checkpoint_url=url,
                               restore_epoch=1)
        running = eng.start()
        await running.join()

    asyncio.run(run_restored())
    rows = [json.loads(l) for l in open(out)]
    assert sum(r["cnt"] for r in rows) == 100_000
