"""State backend tests against a real ParquetBackend writing to a local
directory — the reference's pattern (arroyo-state/src/lib.rs:354-682):
checkpoint -> restore round-trips per table type, key-range-filtered restore
(rescaling), delete tombstones, epoch cleanup, and a full pipeline
crash/restore with exactly-once output."""

import asyncio
import json
import uuid

import numpy as np
import pytest

from arroyo_tpu import AggKind, AggSpec, Batch, Stream
from arroyo_tpu.connectors.memory import clear_sink, sink_output
from arroyo_tpu.engine.engine import Engine
from arroyo_tpu.state.backend import ParquetBackend, TableSnapshot
from arroyo_tpu.state.store import StateStore
from arroyo_tpu.state.tables import TableDescriptor, TableType
from arroyo_tpu.types import StopMode, TaskInfo

SEC = 1_000_000


@pytest.fixture
def backend(tmp_path):
    return ParquetBackend.for_url(f"file://{tmp_path}")


def fresh_task(parallelism=1, idx=0):
    return TaskInfo(f"job-{uuid.uuid4().hex[:8]}", "op-1", "test", idx,
                    parallelism)


def test_kv_tables_roundtrip(backend):
    task = fresh_task()
    store = StateStore(task, backend)
    g = store.get_global_keyed_state("g")
    g.insert("offset", 42)
    k = store.get_keyed_state("k")
    k.insert(100, 7, {"a": 1})
    tkm = store.get_time_key_map("t")
    tkm.insert(10, "x", 1.5)
    tkm.insert(20, "y", 2.5)
    ktm = store.get_key_time_multi_map("m")
    ktm.insert(10, 5, "v1")
    ktm.insert(11, 5, "v2")
    store.checkpoint(1, watermark=12345)

    store2 = StateStore(task, backend, restore_epoch=1)
    assert store2.restore_watermark() == 12345
    assert store2.get_global_keyed_state("g").get("offset") == 42
    assert store2.get_keyed_state("k").get(7) == {"a": 1}
    assert store2.get_time_key_map("t").get(20, "y") == 2.5
    assert store2.get_key_time_multi_map("m").get_time_range(5, 0, 100) == \
        ["v1", "v2"]


def test_batch_buffer_roundtrip(backend):
    task = fresh_task()
    store = StateStore(task, backend)
    buf = store.get_batch_buffer("b")
    b = Batch(np.array([1, 2, 3], dtype=np.int64),
              {"k": np.array([10, 20, 30], dtype=np.int64),
               "s": np.array(["a", "b", "c"], dtype=object)}).with_key(["k"])
    buf.append(b)
    store.checkpoint(1, None)

    store2 = StateStore(task, backend, restore_epoch=1)
    buf2 = store2.get_batch_buffer("b")
    restored = buf2.all()
    assert restored is not None and len(restored) == 3
    assert restored.key_hash is not None
    assert list(restored.columns["s"]) == ["a", "b", "c"]


def test_keyed_restore_filters_by_key_range(backend):
    """Rescale 1 -> 2: each new subtask only restores keys it owns
    (parquet.rs:194-218 semantics)."""
    task = fresh_task(parallelism=1)
    store = StateStore(task, backend)
    k = store.get_keyed_state("k")
    rng = np.random.default_rng(1)
    hashes = rng.integers(0, 1 << 63, 100, dtype=np.uint64) * 2
    for h in hashes.tolist():
        k.insert(0, int(h), h % 97)
    store.checkpoint(1, None)

    total = 0
    for idx in range(2):
        t2 = TaskInfo(task.job_id, task.operator_id, "test", idx, 2)
        s2 = StateStore(t2, backend, restore_epoch=1)
        k2 = s2.get_keyed_state("k")
        lo, hi = t2.key_range
        for key, _ in k2.items():
            assert lo <= key <= hi
        total += len(k2)
    assert total == len(set(hashes.tolist()))


def test_delete_tombstones(backend):
    task = fresh_task()
    store = StateStore(task, backend)
    k = store.get_keyed_state("k")
    k.insert(0, 1, "keep")
    k.insert(0, 2, "remove")
    store.checkpoint(1, None)
    k.remove(2)
    store.note_delete("k", 2)
    store.checkpoint(2, None)

    s2 = StateStore(task, backend, restore_epoch=2)
    k2 = s2.get_keyed_state("k")
    assert k2.get(1) == "keep"
    assert k2.get(2) is None


def test_epoch_cleanup(backend):
    task = fresh_task()
    for epoch in (1, 2, 3):
        store = StateStore(task, backend)
        store.get_global_keyed_state("g").insert("e", epoch)
        store.checkpoint(epoch, None)
    backend.cleanup_before(task.job_id, 3)
    files = backend.storage.list(f"{task.job_id}/checkpoints")
    assert files and all("checkpoint-0000003" in f for f in files)


def test_pipeline_crash_restore_exactly_once(tmp_path):
    """Full engine: run with checkpoints, 'crash', restore from the last
    epoch, and verify windowed output is exactly-once (no duplicates, no
    gaps) — the reference's smoke-test pattern."""
    url = f"file://{tmp_path}/ckpt"
    out_path = f"{tmp_path}/out.jsonl"
    job = "restore-job"
    total = 3000

    def build():
        return (Stream.source("impulse", {
                    "event_rate": 30_000.0, "message_count": total,
                    "event_time_interval_micros": 1000, "batch_size": 100})
                .watermark(max_lateness_micros=0)
                .map(lambda c: {"counter": c["counter"],
                                "bucket": c["counter"] % 7}, name="b")
                .key_by("bucket")
                .tumbling_aggregate(
                    100 * 1000, [AggSpec(AggKind.COUNT, None, "cnt"),
                                 AggSpec(AggKind.SUM, "counter", "sum_c")])
                .sink("single_file", {"path": out_path}))

    async def run_with_crash():
        eng = Engine.for_local(build(), job, checkpoint_url=url)
        running = eng.start()
        await asyncio.sleep(0.04)
        await running.checkpoint(1)
        # an epoch is restorable only once all subtasks completed it
        assert await running.wait_for_checkpoint(1)
        # crash: stop immediately without letting it finish
        await running.stop(StopMode.IMMEDIATE)
        try:
            await running.join()
        except RuntimeError:
            pass

    asyncio.run(run_with_crash())

    async def run_restored():
        eng = Engine.for_local(build(), job, checkpoint_url=url,
                               restore_epoch=1)
        running = eng.start()
        await running.join()

    asyncio.run(run_restored())

    rows = [json.loads(l) for l in open(out_path)]
    # every counter value 0..total-1 counted exactly once across windows
    assert sum(r["cnt"] for r in rows) == total
    assert sum(r["sum_c"] for r in rows) == total * (total - 1) // 2
    # no duplicate (bucket, window_end) rows
    seen = set()
    for r in rows:
        key = (r["bucket"], r["window_end"])
        assert key not in seen, f"duplicate window emission {key}"
        seen.add(key)
