"""Decode/egress parity matrix (zero-copy vectorized ingest PR).

The vectorized serde fast paths (formats.py: pyarrow NDJSON reader,
bulk array parse, template-based JSON egress) must emit rows IDENTICAL
to the legacy row-at-a-time path on exactly the fixtures the legacy
docstrings pin: nullable bools staying bool-typed object columns,
digit strings staying strings, missing fields becoming NaN/object
columns, and Debezium ``__op`` envelopes.  The matrix runs every
fixture through all three decode paths (arrow / bulk / legacy) and
both egress paths, plus the schema-drift mid-stream fallback and the
``ARROYO_FAST_DECODE=0`` full escape.
"""

import json

import numpy as np
import pytest

from arroyo_tpu.formats import (
    JsonFormat,
    batch_to_rows,
    encode_json_lines,
    fast_decode_enabled,
    make_format,
)
from arroyo_tpu.types import Batch

try:
    import pyarrow  # noqa: F401
    import pyarrow.json  # noqa: F401

    HAVE_ARROW = True
except ImportError:  # pragma: no cover - image always has pyarrow
    HAVE_ARROW = False

needs_arrow = pytest.mark.skipif(not HAVE_ARROW, reason="pyarrow absent")


def _decode(payloads, mode, monkeypatch, ts_field=None, **fmt_kwargs):
    """Rows out of one decode path: 'legacy' (ARROYO_FAST_DECODE=0),
    'bulk' (fast, pyarrow latched off), or 'arrow' (fast)."""
    fmt = JsonFormat(**fmt_kwargs)
    if mode == "legacy":
        monkeypatch.setenv("ARROYO_FAST_DECODE", "0")
    else:
        monkeypatch.setenv("ARROYO_FAST_DECODE", "1")
        if mode == "bulk":
            fmt._arrow_ok = False
    try:
        batch = fmt.batch(payloads, ts_field)
    finally:
        monkeypatch.delenv("ARROYO_FAST_DECODE", raising=False)
    return batch


FAST_MODES = (["arrow"] if HAVE_ARROW else []) + ["bulk"]

# the tricky fixtures the rows_to_columns docstring pins -------------------

FIXTURES = {
    "nullable_bools": [{"f": True, "i": 1}, {"f": None, "i": 2},
                       {"f": False, "i": 3}],
    "digit_strings": [{"s": "01234", "n": 5}, {"s": "99", "n": 6}],
    "missing_numeric": [{"a": 1, "b": 2.5}, {"b": 3.5}, {"a": 4}],
    "missing_strings": [{"s": "x", "k": 1}, {"k": 2}],
    "all_null_column": [{"x": None, "k": 1}, {"x": None, "k": 2}],
    "unicode_strings": [{"s": "café ☃", "k": 1},
                        {"s": "line\nbreak \"q\"", "k": 2}],
    "int_float_mix": [{"v": 1, "k": 1}, {"v": 2.5, "k": 2}],
    "scalar_payloads": [1, "two", 3.5],
    "array_payloads": "arrays",  # special-cased below
}


def _payloads(name):
    fixture = FIXTURES[name]
    if name == "scalar_payloads":
        return [json.dumps(v).encode() for v in fixture]
    if name == "array_payloads":
        return [json.dumps([{"a": 1, "b": "x"}, {"a": 2, "b": "y"}]).encode()]
    return [json.dumps(r).encode() for r in fixture]


@pytest.mark.parametrize("fixture", sorted(FIXTURES))
@pytest.mark.parametrize("mode", FAST_MODES)
def test_decode_parity_matrix(fixture, mode, monkeypatch):
    """Every fast decode path emits the exact rows the legacy path does
    (NaN/None normalization via batch_to_rows, which both share)."""
    payloads = _payloads(fixture)
    legacy = _decode(payloads, "legacy", monkeypatch)
    fast = _decode(payloads, mode, monkeypatch)
    assert batch_to_rows(fast) == batch_to_rows(legacy)


@pytest.mark.parametrize("mode", FAST_MODES)
def test_decode_parity_column_dtypes(mode, monkeypatch):
    """Beyond row equality: the pinned dtype semantics survive the fast
    paths — digit strings stay strings, nullable bools stay bool-typed
    object columns, missing ints become NaN float64."""
    payloads = _payloads("digit_strings")
    fast = _decode(payloads, mode, monkeypatch)
    assert fast.columns["s"].dtype == object
    assert list(fast.columns["s"]) == ["01234", "99"]

    fast = _decode(_payloads("nullable_bools"), mode, monkeypatch)
    assert fast.columns["f"].dtype == object
    assert list(fast.columns["f"]) == [True, None, False]

    fast = _decode(_payloads("missing_numeric"), mode, monkeypatch)
    a = fast.columns["a"]
    assert a.dtype == np.float64
    assert a[0] == 1.0 and np.isnan(a[1]) and a[2] == 4.0


@pytest.mark.parametrize("mode", FAST_MODES)
def test_decode_parity_timestamp_field(mode, monkeypatch):
    payloads = [json.dumps({"ts": 100 + i, "v": i}).encode()
                for i in range(4)]
    legacy = _decode(payloads, "legacy", monkeypatch, ts_field="ts")
    fast = _decode(payloads, mode, monkeypatch, ts_field="ts")
    assert fast.timestamp.tolist() == legacy.timestamp.tolist()
    assert fast.timestamp.dtype == np.int64


def test_debezium_envelopes_identical_fast_and_legacy(monkeypatch):
    """Debezium is a designated row path: fast on/off must be
    bit-identical (the envelope carries per-row op semantics)."""
    payloads = [
        json.dumps({"payload": {"before": None,
                                "after": {"id": 1, "v": "a"},
                                "op": "c"}}).encode(),
        json.dumps({"payload": {"before": {"id": 1, "v": "a"},
                                "after": {"id": 1, "v": "b"},
                                "op": "u"}}).encode(),
        json.dumps({"payload": {"before": {"id": 1, "v": "b"},
                                "after": None, "op": "d"}}).encode(),
    ]
    rows = {}
    for flag in ("0", "1"):
        monkeypatch.setenv("ARROYO_FAST_DECODE", flag)
        fmt = make_format("debezium_json")
        rows[flag] = batch_to_rows(fmt.batch(payloads))
    assert rows["1"] == rows["0"]
    assert [r["__op"] for r in rows["1"]] == [
        "append", "retract", "append", "retract"]


@needs_arrow
def test_schema_lock_and_mid_stream_drift_fallback(monkeypatch):
    """First batch locks the stream's Arrow schema; a mid-stream type
    conflict (schema drift) re-infers instead of crashing, and the
    drifted batch still matches the legacy rows."""
    monkeypatch.setenv("ARROYO_FAST_DECODE", "1")
    fmt = JsonFormat()
    b1 = [json.dumps({"v": i, "k": i}).encode() for i in range(3)]
    fmt.batch(b1)
    locked = fmt._pa_schema
    assert locked is not None and "v" in locked.names
    # same shape: the lock holds (no re-inference, same schema object
    # semantics) and rows stay correct
    out2 = fmt.batch(b1)
    assert out2.columns["v"].dtype == np.int64
    # drift: v becomes a string — explicit-schema parse fails, the
    # stream re-locks on the inferred schema, rows match legacy
    b3 = [json.dumps({"v": "zero", "k": 0}).encode(),
          json.dumps({"v": "one", "k": 1}).encode()]
    out3 = fmt.batch(b3)
    legacy = _decode(b3, "legacy", monkeypatch)
    assert batch_to_rows(out3) == batch_to_rows(legacy)
    assert fmt._pa_schema is not None and not fmt._pa_schema.equals(locked)
    # drift must not latch the fast path off
    assert getattr(fmt, "_arrow_ok", True) is not False


@needs_arrow
def test_schema_lock_null_fills_absent_fields(monkeypatch):
    """Column-set stability under the locked schema: a field absent
    from a later batch null-fills instead of vanishing (keeps the
    coalescer/data-plane signatures from flapping mid-stream)."""
    monkeypatch.setenv("ARROYO_FAST_DECODE", "1")
    fmt = JsonFormat()
    fmt.batch([json.dumps({"a": 1, "b": 2}).encode()])
    out = fmt.batch([json.dumps({"a": 3}).encode()])
    assert "b" in out.columns
    assert np.isnan(out.columns["b"][0])


def test_bulk_path_latches_off_after_repeated_failures(monkeypatch):
    """Payloads the array join mis-frames stop paying the doomed
    join+parse after 3 consecutive failures (the row path answers)."""
    monkeypatch.setenv("ARROYO_FAST_DECODE", "1")
    fmt = JsonFormat()
    fmt._arrow_ok = False
    # a UTF-8 BOM parses per payload (json detects utf-8-sig) but makes
    # the bulk [p1,p2] array framing invalid JSON — the row path must
    # answer every time and the stream must stop paying the join+parse
    bad = [b"\xef\xbb\xbf" + json.dumps({"a": 1}).encode(),
           b"\xef\xbb\xbf" + json.dumps({"a": 2}).encode()]
    for _ in range(4):
        out = fmt.batch(bad)
        assert out.columns["a"].tolist() == [1, 2]
    assert fmt._bulk_fails >= 3


def test_fast_decode_escape_reads_env_per_call(monkeypatch):
    monkeypatch.setenv("ARROYO_FAST_DECODE", "0")
    assert not fast_decode_enabled()
    monkeypatch.setenv("ARROYO_FAST_DECODE", "1")
    assert fast_decode_enabled()


# -- egress ----------------------------------------------------------------


def _tricky_batch():
    f = np.array([1.5, np.nan, np.inf, -np.inf], dtype=np.float64)
    return Batch(
        np.arange(4, dtype=np.int64),
        {
            "i": np.array([1, -2, 3, 40], dtype=np.int64),
            "f": f,
            "b": np.array([True, False, True, False]),
            "nb": np.array([True, None, False, None], dtype=object),
            "s": np.array(["01234", 'q"uote', "café", "x\ny"],
                          dtype=object),
        },
    )


def test_egress_parity_tricky_columns(monkeypatch):
    """serialize_batch fast vs legacy: byte-identical payloads across
    NaN/inf floats, nullable bools, digit strings and escapes."""
    batch = _tricky_batch()
    fmt = JsonFormat()
    monkeypatch.setenv("ARROYO_FAST_DECODE", "0")
    legacy = fmt.serialize_batch(batch)
    monkeypatch.setenv("ARROYO_FAST_DECODE", "1")
    fast = fmt.serialize_batch(batch)
    assert fast == legacy
    # every line re-parses (NaN became null on this path, like _py)
    parsed = [json.loads(p) for p in fast]
    assert parsed[1]["f"] is None and parsed[0]["f"] == 1.5


def test_egress_decode_roundtrip_parity(monkeypatch):
    """fast-encode -> fast-decode round trip equals the legacy-legacy
    round trip row for row (the two halves compose)."""
    batch = Batch(
        np.arange(3, dtype=np.int64),
        {"a": np.array([1, 2, 3], dtype=np.int64),
         "s": np.array(["x", "01", "z"], dtype=object),
         "f": np.array([0.5, np.nan, 2.0])})
    fmt = JsonFormat()
    rows = {}
    for flag in ("0", "1"):
        monkeypatch.setenv("ARROYO_FAST_DECODE", flag)
        f2 = JsonFormat()
        rows[flag] = batch_to_rows(f2.batch(fmt.serialize_batch(batch)))
    assert rows["1"] == rows["0"]


def test_encode_json_lines_falls_back_on_nested_columns():
    """Columns the cell encoders can't express (nested dicts) return
    None — serialize_batch then matches the legacy row path output."""
    batch = Batch(
        np.arange(2, dtype=np.int64),
        {"k": np.array([1, 2], dtype=np.int64),
         "nest": np.array([{"a": 1}, {"b": 2}], dtype=object)})
    assert encode_json_lines(batch) is None
    fmt = JsonFormat()
    fast = fmt.serialize_batch(batch)
    legacy = fmt.serialize(batch_to_rows(batch))
    assert fast == legacy


def test_encode_json_lines_matches_json_dumps_layout():
    """Template rendering reproduces json.dumps' exact separators and
    escaping, including a column name that contains a % sign."""
    batch = Batch(
        np.arange(2, dtype=np.int64),
        {"p%ct": np.array([1, 2], dtype=np.int64),
         "s": np.array(["a", "b"], dtype=object)})
    lines = encode_json_lines(batch)
    expected = [json.dumps({"p%ct": 1, "s": "a"}),
                json.dumps({"p%ct": 2, "s": "b"})]
    assert lines == expected


def test_single_file_fast_path_pins_formats_semantics(monkeypatch):
    """The single_file connector's fast path decodes through formats.py
    (digit strings STAY strings, missing fields stay None) while the
    ``ARROYO_FAST_DECODE=0`` escape reproduces the connector's
    historical ad-hoc pivot bit-for-bit — which coerced an
    object-dtype digit-string column (one produced by missing values)
    to float64.  Both behaviors are pinned ON PURPOSE: the divergence
    on this corner is the documented semantic unification, not an
    accident (docs/operations.md § Ingest & egress)."""
    from arroyo_tpu.connectors.single_file import _rows_to_batch

    rows = [{"id": 0, "ts": 1}, {"id": 1, "code": "105", "ts": 2}]
    payloads = [json.dumps(r).encode() for r in rows]

    legacy = _rows_to_batch([json.loads(p) for p in payloads], "ts")
    # historical connector pivot: object column of digit strings with a
    # missing value coerces to float64 (None -> nan, "105" -> 105.0)
    assert legacy.columns["code"].dtype == np.float64
    assert np.isnan(legacy.columns["code"][0])
    assert legacy.columns["code"][1] == 105.0

    monkeypatch.setenv("ARROYO_FAST_DECODE", "1")
    fast = JsonFormat().batch(payloads, "ts")
    # formats.py pinned semantics: digit strings stay strings, the
    # missing field stays None (object column)
    assert fast.columns["code"].dtype == object
    assert fast.columns["code"][0] is None
    assert fast.columns["code"][1] == "105"
