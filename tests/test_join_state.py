"""Partition-adaptive join state + multi-way join planning (PR 6).

Covers: the incrementally maintained sorted runs (merge-vs-naive
parity under random appends), valid-range TTL eviction + amortized
compaction, PartitionedJoinBuffer's BatchBuffer-contract parity, the
hot/cold device-residency policy (deterministic promotions, probe
parity with the device rings forced on), sanitized end-to-end parity of
partitioned vs legacy state across the device/probe knob matrix,
null-keyed-row retirement (inner joins no longer buffer rows that can
never emit), the cascaded-join -> multi-way rewrite (plan shape +
row equivalence, windowed and TTL), and the headline round-trip: a
partitioned join state checkpointed mid-stream and restored at a
DIFFERENT parallelism with exactly-once output."""

import asyncio
import json

import numpy as np
import pytest

from arroyo_tpu.connectors.memory import clear_sink, sink_output
from arroyo_tpu.engine.engine import Engine, LocalRunner
from arroyo_tpu.sql import plan_sql
from arroyo_tpu.state.join_state import PartitionedJoinBuffer
from arroyo_tpu.state.tables import BatchBuffer
from arroyo_tpu.types import Batch

SEC = 1_000_000


def _mk_batch(keys, ts=None, extra=None):
    keys = np.asarray(keys, dtype=np.uint64)
    n = len(keys)
    ts = (np.asarray(ts, dtype=np.int64) if ts is not None
          else np.arange(n, dtype=np.int64))
    cols = {"k": keys.astype(np.int64),
            "v": np.arange(n, dtype=np.int64)}
    if extra:
        cols.update(extra)
    return Batch(ts, cols, keys, ("k",))


# -- sorted-run maintenance --------------------------------------------------


def test_incremental_merge_matches_full_sort():
    """Random append sequence: every partition's sorted run must equal a
    stable full sort of its storage after each merge."""
    rng = np.random.default_rng(7)
    buf = PartitionedJoinBuffer(n_partitions=4)
    for step in range(12):
        n = int(rng.integers(1, 200))
        keys = rng.integers(0, 50, n).astype(np.uint64)
        buf.append(_mk_batch(keys, ts=rng.integers(0, 1000, n)))
        for part in buf.parts:
            m = part.n
            if m == 0:
                continue
            ref = np.argsort(part.keys[:m], kind="stable")
            np.testing.assert_array_equal(part.order[:m], ref)
            np.testing.assert_array_equal(part.skeys[:m],
                                          part.keys[:m][ref])
            np.testing.assert_array_equal(part.sts[:m], part.ts[:m][ref])


def test_probe_batch_matches_legacy_join(monkeypatch):
    """probe_batch must produce the same (my row, state row) pair
    multiset and the same unmatched mask as the legacy full re-sort."""
    monkeypatch.setenv("ARROYO_DEVICE_JOIN", "off")
    from arroyo_tpu.ops.join import join_pairs

    rng = np.random.default_rng(3)
    state = PartitionedJoinBuffer(n_partitions=8)
    skeys = rng.integers(0, 40, 300).astype(np.uint64)
    state.append(_mk_batch(skeys))
    probe = _mk_batch(rng.integers(0, 60, 97).astype(np.uint64))

    bsel, rows, counts = state.probe_batch(probe)
    got = sorted(zip(probe.key_hash[bsel].tolist(),
                     rows.columns["v"].tolist()))

    lo, ro, lidx, ridx, ref_counts = join_pairs(probe.key_hash, skeys)
    sb = _mk_batch(skeys)
    want = sorted(zip(probe.key_hash[lo[lidx]].tolist(),
                      sb.columns["v"][ro[ridx]].tolist()))
    assert got == want
    want_unmatched = np.zeros(len(probe), dtype=bool)
    want_unmatched[lo[ref_counts == 0]] = True
    np.testing.assert_array_equal(counts == 0, want_unmatched)


def test_ttl_is_valid_range_advance_then_compaction():
    buf = PartitionedJoinBuffer(n_partitions=2)
    keys = np.arange(4000, dtype=np.uint64) % 17
    buf.append(_mk_batch(keys, ts=np.arange(4000, dtype=np.int64)))
    assert len(buf) == 4000
    # advance: no data movement until dead rows dominate
    buf.evict_before(1000)
    assert len(buf) == 3000
    assert sum(p.n for p in buf.parts) == 4000, \
        "a lone advance must not move data"
    probe = _mk_batch(np.array([3], dtype=np.uint64), ts=[0])
    _b, rows, _c = buf.probe_batch(probe)
    assert (rows.timestamp >= 1000).all()
    # per-batch watermark cadence past the half-dead threshold: the
    # (throttled, every-8th-advance) dead rescan triggers compaction
    for t in range(1100, 3600, 100):
        buf.evict_before(t)
    assert len(buf) == 500
    total = sum(p.n for p in buf.parts)
    # the throttled rescan at t=2500 compacted 4000 -> 1500 resident
    # rows; once partitions fall under the 1024-row scan floor further
    # dead rows stay resident by design (not worth the scan)
    assert total <= 1500, "compaction should have dropped dead rows"
    for part in buf.parts:
        m = part.n
        ref = np.argsort(part.keys[:m], kind="stable")
        np.testing.assert_array_equal(part.order[:m], ref)


def test_snapshot_restore_roundtrip_and_contains():
    buf = PartitionedJoinBuffer(n_partitions=4)
    buf.append(_mk_batch([1, 2, 3, 2, 9], ts=[10, 20, 30, 40, 50]))
    buf.evict_before(15)
    snap = buf.snapshot_batch()
    assert len(snap) == 4  # ts=10 row is dead
    back = PartitionedJoinBuffer(n_partitions=4)
    back.restore_batch(snap)
    assert len(back) == 4
    np.testing.assert_array_equal(
        back.contains_keys(np.array([1, 2, 7], dtype=np.uint64)),
        [False, True, False])
    # legacy interchange: the same snapshot restores into a flat buffer
    legacy = BatchBuffer()
    legacy.restore_batch(snap)
    assert len(legacy) == 4


def test_hot_promotion_deterministic_and_probe_parity(monkeypatch):
    """With the device path forced on, the hot-set sequence must depend
    only on the data stream — two identical runs promote identically —
    and probes against device rings must equal host probes."""
    monkeypatch.setenv("ARROYO_JOIN_HOT_MIN_ROWS", "64")
    from arroyo_tpu.obs import perf

    def run(device: str):
        monkeypatch.setenv("ARROYO_DEVICE_JOIN", device)
        rng = np.random.default_rng(11)
        buf = PartitionedJoinBuffer(n_partitions=4)
        outs = []
        promos = []
        for _ in range(8):
            keys = (rng.integers(0, 8, 400) * 4).astype(np.uint64)
            # all keys land in partition 0 -> it must become hot
            buf.append(_mk_batch(keys))
            probe = _mk_batch(rng.integers(0, 40, 50).astype(np.uint64))
            bsel, rows, counts = buf.probe_batch(probe)
            outs.append((np.sort(bsel).tolist(), counts.tolist(),
                         sorted(rows.columns["v"].tolist())))
            promos.append(sum(1 for p in buf.parts
                              if p.dev is not None))
        return outs, promos

    outs_on_1, promos_1 = run("on")
    outs_on_2, promos_2 = run("on")
    outs_off, _ = run("off")
    assert promos_1 == promos_2, "promotion must be deterministic"
    assert promos_1[-1] >= 1, "the skewed partition should be hot"
    assert outs_on_1 == outs_on_2 == outs_off, \
        "device rings must not change probe results"


# -- PR 15: split-hash rings + device-resident payload planes ----------------


def _dev_rows():
    from arroyo_tpu.obs import perf

    return perf.counter("join_device_gather_rows")


def _payload_buf(monkeypatch, payload="auto", parts=1):
    """A buffer whose partitions promote on the first append (floor 1)
    with the requested payload policy."""
    monkeypatch.setenv("ARROYO_DEVICE_JOIN", "on")
    monkeypatch.setenv("ARROYO_JOIN_HOT_MIN_ROWS", "1")
    monkeypatch.setenv("ARROYO_JOIN_PAYLOAD_DEVICE", payload)
    return PartitionedJoinBuffer(n_partitions=parts)


def test_split_hash_helpers_preserve_order_and_pad_exactness():
    """The biased-i32 image of the top 32 hash bits must sort exactly
    like the u64 keys, and runs whose keys collide with the hi pad must
    refuse staging (exactness over speed)."""
    from arroyo_tpu.ops.join import ring_stageable, split_hi32, split_lo32

    rng = np.random.default_rng(5)
    keys = rng.integers(0, 1 << 63, 4096, dtype=np.uint64) * np.uint64(3)
    hi = split_hi32(keys)
    order_u64 = np.argsort(keys >> np.uint64(32), kind="stable")
    order_i32 = np.argsort(hi, kind="stable")
    np.testing.assert_array_equal(order_u64, order_i32)
    # lo plane is a bit-view: hi+lo reconstructs the key
    lo = split_lo32(keys).view(np.uint32).astype(np.uint64)
    hi_u = ((hi.view(np.uint32) ^ np.uint32(0x80000000))
            .astype(np.uint64) << np.uint64(32))
    np.testing.assert_array_equal(hi_u | lo, keys)
    assert ring_stageable(keys)
    assert not ring_stageable(
        np.array([np.uint64(0xFFFFFFFF) << np.uint64(32)], np.uint64))


def test_i32_collision_rows_die_in_the_verify(monkeypatch):
    """Keys equal in the top 32 bits but distinct in the low 32 are
    probe CANDIDATES on the hi plane; the full-key verify must kill
    them — on device in the fused expand+gather dispatch (payload
    rings) and against the host mirror on the keys-only path."""
    twin_a = (np.uint64(0x42) << np.uint64(32)) | np.uint64(5)
    twin_b = (np.uint64(0x42) << np.uint64(32)) | np.uint64(9)

    for payload in ("auto", "off"):
        buf = _payload_buf(monkeypatch, payload)
        buf.append(_mk_batch(np.array([twin_a] * 3 + [7], np.uint64)))
        ring = buf.parts[0].dev
        assert ring is not None
        assert (ring.plan is not None) == (payload == "auto")
        probe = _mk_batch(np.array([twin_b], np.uint64))
        bsel, rows, counts = buf.probe_batch(probe)
        assert len(bsel) == 0 and counts.tolist() == [0], \
            f"i32-collision row survived the {payload} verify"
        bsel, rows, _c = buf.probe_batch(
            _mk_batch(np.array([twin_a], np.uint64)))
        assert len(bsel) == 3 and set(rows.key_hash) == {twin_a}


def test_hi_pad_collision_keeps_partition_host(monkeypatch):
    """A key whose top 32 bits are all ones is ambiguous with the ring
    pad: the partition must refuse staging and stay exact on host."""
    buf = _payload_buf(monkeypatch)
    bad = (np.uint64(0xFFFFFFFF) << np.uint64(32)) | np.uint64(3)
    buf.append(_mk_batch(np.array([bad, 11, 11], np.uint64)))
    assert buf.parts[0].dev is None, "unstageable run got a ring"
    bsel, rows, _c = buf.probe_batch(_mk_batch(np.array([bad], np.uint64)))
    assert len(bsel) == 1 and rows.key_hash[0] == bad


@pytest.mark.slow
def test_payload_probe_batch_parity_and_counters(monkeypatch):
    """The fused device gather must emit bit-identical rows (every
    dtype kind the planes transport: f8/f4/i8/i4/u8/bool) to the host
    fancy-index across appends, TTL eviction and regrows — and the
    device/host split must land in the gather counters."""
    rng = np.random.default_rng(23)

    def extra(n):
        return {
            "f8": rng.normal(size=n),
            "f4": rng.normal(size=n).astype(np.float32),
            "i4": rng.integers(-50, 50, n).astype(np.int32),
            "u8": rng.integers(0, 1 << 60, n).astype(np.uint64),
            "b": rng.integers(0, 2, n).astype(bool),
        }

    def run(payload):
        rng.bit_generator.state = state0
        buf = _payload_buf(monkeypatch, payload, parts=4)
        d0 = _dev_rows()
        outs = []
        for step in range(6):
            n = int(rng.integers(50, 300))
            keys = rng.integers(0, 60, n).astype(np.uint64)
            buf.append(_mk_batch(keys, ts=rng.integers(0, 1000, n),
                                 extra=extra(n)))
            if step == 3:
                buf.evict_before(400)
            probe = _mk_batch(rng.integers(0, 80, 70).astype(np.uint64))
            bsel, rows, counts = buf.probe_batch(probe)
            order = np.lexsort((rows.timestamp, rows.key_hash, bsel))
            outs.append((bsel[order].tolist(), counts.tolist(),
                         rows.timestamp[order].tolist(),
                         {c: v[order].tolist()
                          for c, v in sorted(rows.columns.items())},
                         {c: str(v.dtype)
                          for c, v in rows.columns.items()}))
        return outs, _dev_rows() - d0

    state0 = rng.bit_generator.state
    outs_on, dev_on = run("auto")
    outs_off, dev_off = run("off")
    assert outs_on == outs_off
    assert dev_on > 0, "payload rings never emitted through the device"
    assert dev_off == 0, "payload=off still device-gathered"


def test_string_payload_sticky_host_fallback(monkeypatch):
    """The first string column flips the buffer's STICKY host-gather
    fallback: rings stay keys-only for the buffer's whole life (even
    for later all-numeric batches), every match host-gathers, and the
    stats report zero payload rings."""
    buf = _payload_buf(monkeypatch)
    d0 = _dev_rows()
    tags = np.array(["a", "b", "c", "a"], dtype=object)
    buf.append(_mk_batch([1, 2, 3, 1], extra={"tag": tags}))
    buf.append(_mk_batch([4, 5]))  # numeric-only later batch
    ring = buf.parts[0].dev
    assert ring is not None and ring.plan is None, \
        "string schema must keep rings keys-only"
    assert buf.stats()["payload_rings"] == 0
    bsel, rows, _c = buf.probe_batch(_mk_batch([1, 9]))
    assert len(bsel) == 2
    assert sorted(rows.columns["tag"].tolist()) == ["a", "a"]
    assert _dev_rows() == d0, "sticky-host buffer used the device gather"


def test_payload_checkpoint_roundtrip_with_resident_rings(monkeypatch):
    """snapshot_batch with payload rings resident must capture exactly
    the live rows (the host mirror is authoritative), and the restored
    buffer re-promotes payload rings and answers probes identically."""
    rng = np.random.default_rng(31)
    buf = _payload_buf(monkeypatch, parts=4)
    for _ in range(3):
        n = 200
        buf.append(_mk_batch(rng.integers(0, 40, n).astype(np.uint64),
                             ts=rng.integers(0, 1000, n),
                             extra={"f8": rng.normal(size=n)}))
    buf.evict_before(300)
    assert buf.stats()["payload_rings"] >= 1
    snap = buf.snapshot_batch()
    back = PartitionedJoinBuffer(n_partitions=4)
    back.restore_batch(snap)
    assert len(back) == len(buf)
    assert back.stats()["payload_rings"] >= 1, \
        "restore must re-promote payload rings"
    probe = _mk_batch(rng.integers(0, 50, 64).astype(np.uint64))
    bsel_a, rows_a, counts_a = buf.probe_batch(probe)
    bsel_b, rows_b, counts_b = back.probe_batch(probe)
    key_a = sorted(zip(bsel_a.tolist(), rows_a.timestamp.tolist(),
                       rows_a.columns["f8"].tolist()))
    key_b = sorted(zip(bsel_b.tolist(), rows_b.timestamp.tolist(),
                       rows_b.columns["f8"].tolist()))
    assert counts_a.tolist() == counts_b.tolist()
    assert key_a == key_b


def test_payload_rings_spread_over_mesh(monkeypatch):
    """Payload planes ride the SAME mesh device their partition's key
    ring pinned (shuffle.partition_device): hot partitions spread over
    the fake 8-device mesh instead of funneling through chip 0."""
    import jax

    monkeypatch.setenv("ARROYO_MESH", "auto")
    rng = np.random.default_rng(17)
    buf = _payload_buf(monkeypatch, parts=8)
    monkeypatch.setenv("ARROYO_JOIN_HOT_PARTITIONS", "8")
    n = 4000
    keys = rng.integers(0, 3000, n).astype(np.uint64)
    b = _mk_batch(keys, ts=rng.integers(0, 1000, n),
                  extra={"f8": rng.normal(size=n)})
    for lo in range(0, n, 1024):
        buf.append(b.select(np.arange(lo, min(lo + 1024, n))))
    stats = buf.stats()
    assert stats["payload_rings"] >= 2, stats
    assert stats["ring_devices"] >= 2, stats
    assert stats["payload_ring_bytes"] > 0
    for p in buf.parts:
        if p.dev is not None and p.dev.plan is not None:
            assert p.dev_device in jax.devices()
            for plane in (p.dev.hi, p.dev.fstack, p.dev.istack):
                assert next(iter(plane.devices())) == p.dev_device, \
                    "payload plane drifted off its key ring's device"


# -- end-to-end parity -------------------------------------------------------

JOIN_SQL = """
CREATE TABLE nexmark WITH (
  connector = 'nexmark', event_rate = '1000000', num_events = '30000',
  rate_limited = 'false', batch_size = '2048',
  base_time_micros = '1700000000000000'
);
WITH b AS (SELECT bid.auction AS auction, bid.price AS price
           FROM nexmark WHERE bid is not null AND bid.price > 40000000),
     a AS (SELECT auction.id AS id, auction.reserve AS reserve
           FROM nexmark WHERE auction is not null)
SELECT X.auction AS auction, X.price AS price, Y.reserve AS reserve
FROM b X JOIN a Y ON X.auction = Y.id
"""


def _run_join_sql(sql=JOIN_SQL, cols=("auction", "price", "reserve")):
    clear_sink("results")
    LocalRunner(plan_sql(sql, parallelism=2)).run()
    return sorted(
        tuple(float(b.columns[c][i]) for c in cols)
        for b in sink_output("results") for i in range(len(b)))


@pytest.mark.parametrize("device,probe,payload", [
    ("off", "search", "off"), ("on", "search", "off"),
    pytest.param("on", "merged", "auto", marks=pytest.mark.slow),
    ("on", "search", "auto")])
def test_partitioned_vs_legacy_identical_rows(monkeypatch, device, probe,
                                              payload):
    """The sanitized parity matrix: partitioned and legacy join state
    must emit identical rows under every device/probe/payload-residency
    configuration (tier-1 conftest keeps ARROYO_SANITIZE armed); the
    hot floor is lowered so the payload combos actually emit through
    resident planes (counter-asserted) instead of vacuously passing."""
    from arroyo_tpu.obs import perf

    monkeypatch.setenv("ARROYO_DEVICE_JOIN", device)
    monkeypatch.setenv("ARROYO_JOIN_PROBE", probe)
    monkeypatch.setenv("ARROYO_JOIN_PAYLOAD_DEVICE", payload)
    monkeypatch.setenv("ARROYO_JOIN_HOT_MIN_ROWS", "16")
    monkeypatch.setenv("ARROYO_JOIN_STATE", "partitioned")
    d0 = perf.counter("join_device_gather_rows")
    part = _run_join_sql()
    dev_rows = perf.counter("join_device_gather_rows") - d0
    assert (dev_rows > 0) == (payload == "auto" and device == "on"), \
        f"device gather rows {dev_rows} vs payload={payload}"
    monkeypatch.setenv("ARROYO_JOIN_STATE", "legacy")
    legacy = _run_join_sql()
    assert part and part == legacy


def test_null_key_rows_never_buffered(monkeypatch):
    """Inner-join sides drop null-keyed (nonce) rows instead of holding
    them until TTL: rows that can never match or pad are pure state
    growth (the round-4 deferral, retired)."""
    from arroyo_tpu.engine.operators_window import (
        JoinWithExpirationOperator,
    )
    from arroyo_tpu.graph.logical import JoinType

    captured = {}
    orig = JoinWithExpirationOperator.handle_watermark

    async def spy(self, watermark, ctx):
        captured["sizes"] = (len(self.left), len(self.right))
        await orig(self, watermark, ctx)

    monkeypatch.setattr(JoinWithExpirationOperator, "handle_watermark",
                        spy)
    sql = """
CREATE TABLE t (k BIGINT, v BIGINT) WITH (
  connector = 'kafka', bootstrap_servers = 'memory://jnull',
  topic = 'x', type = 'source', format = 'json', batch_size = '64',
  max_messages = '6');
SELECT l.v AS lv, r.v AS rv FROM t l JOIN t r ON l.k = r.k
"""
    from arroyo_tpu.connectors.kafka import InMemoryKafkaBroker

    InMemoryKafkaBroker.reset("jnull")
    broker = InMemoryKafkaBroker.get("jnull")
    broker.create_topic("x", partitions=1)
    rows = [{"k": None, "v": 1}, {"k": None, "v": 2}, {"k": 5, "v": 3}]
    for r in rows * 2:
        broker.produce("x", json.dumps(r).encode(), partition=0)
    clear_sink("results")
    LocalRunner(plan_sql(sql)).run()
    out = sorted((int(b.columns["lv"][i]), int(b.columns["rv"][i]))
                 for b in sink_output("results")
                 for i in range(len(b)))
    # only the non-null key joins (with itself, both sides see the rows)
    assert out and all(lv == 3 and rv == 3 for lv, rv in out)
    # the null-keyed rows (4 of 6 per side) were never buffered
    assert captured["sizes"] == (2, 2)


FULL_JOIN_SQL = """
CREATE TABLE nexmark WITH (
  connector = 'nexmark', event_rate = '1000000', num_events = '20000',
  rate_limited = 'false', batch_size = '1024',
  base_time_micros = '1700000000000000'
);
WITH b AS (SELECT bid.auction AS auction, bid.price AS price
           FROM nexmark WHERE bid is not null AND bid.price > 40000000),
     a AS (SELECT auction.id AS id, auction.reserve AS reserve
           FROM nexmark WHERE auction is not null)
SELECT X.auction AS auction, X.price AS price, Y.reserve AS reserve
FROM b X FULL JOIN a Y ON X.auction = Y.id
"""


def test_outer_join_net_state_parity(monkeypatch):
    """FULL OUTER retraction path (probe_batch + rows_with_keys): the
    raw create/delete stream is batch-order dependent, but the NET
    multiset (creates minus deletes per row tuple) must be identical
    between partitioned and legacy state."""
    from collections import Counter

    from arroyo_tpu.types import UPDATE_OP_COLUMN, UpdateOp

    def net(layout):
        monkeypatch.setenv("ARROYO_JOIN_STATE", layout)
        clear_sink("results")
        LocalRunner(plan_sql(FULL_JOIN_SQL, parallelism=2)).run()
        acc = Counter()
        for b in sink_output("results"):
            ops = b.columns[UPDATE_OP_COLUMN]
            for i in range(len(b)):
                row = tuple(
                    None if v != v else float(v) for v in
                    (b.columns["auction"][i], b.columns["price"][i],
                     b.columns["reserve"][i]))
                acc[row] += (-1 if ops[i] == UpdateOp.DELETE.value
                             else 1)
        return +acc  # drop zero-net entries

    part = net("partitioned")
    legacy = net("legacy")
    assert part and part == legacy


# -- multi-way rewrite -------------------------------------------------------

MW_SQL = """
CREATE TABLE nexmark WITH (
  connector = 'nexmark', event_rate = '1000000', num_events = '30000',
  rate_limited = 'false', batch_size = '2048',
  base_time_micros = '1700000000000000'
);
SELECT P.id AS id, P.np AS np, A.na AS na, B.nb AS nb
FROM (
  SELECT person.id AS id, TUMBLE(INTERVAL '10' SECOND) AS window,
         count(*) AS np FROM nexmark WHERE person is not null GROUP BY 1, 2
) AS P
JOIN (
  SELECT auction.seller AS seller, TUMBLE(INTERVAL '10' SECOND) AS window,
         count(*) AS na FROM nexmark WHERE auction is not null GROUP BY 1, 2
) AS A ON P.id = A.seller AND P.window = A.window
JOIN (
  SELECT bid.bidder AS bidder, TUMBLE(INTERVAL '10' SECOND) AS window,
         count(*) AS nb FROM nexmark WHERE bid is not null GROUP BY 1, 2
) AS B ON P.id = B.bidder AND P.window = B.window
"""


def _kinds(prog):
    return sorted(prog.node(n).operator.kind.value
                  for n in prog.graph.nodes if "join" in n)


def test_multiway_rewrite_plan_shape_and_equivalence(monkeypatch):
    """A cascade of INNER equi-joins on one key must plan as ONE
    multi-way join (no pairwise intermediates) and emit exactly the
    rows of the nested pairwise plan."""
    def run(mw):
        monkeypatch.setenv("ARROYO_MULTIWAY", mw)
        prog = plan_sql(MW_SQL, parallelism=2)
        clear_sink("results")
        LocalRunner(prog).run()
        rows = sorted(
            (int(b.columns["id"][i]), int(b.columns["np"][i]),
             int(b.columns["na"][i]), int(b.columns["nb"][i]))
            for b in sink_output("results") for i in range(len(b)))
        return prog, rows

    prog_on, rows_on = run("1")
    prog_off, rows_off = run("0")
    assert _kinds(prog_on) == ["multi_way_join"]
    assert _kinds(prog_off) == ["window_join", "window_join"]
    assert rows_on and rows_on == rows_off


def test_multiway_rewrite_validates():
    from arroyo_tpu.analysis.plan_validator import (
        errors_of,
        validate_program,
    )

    prog = plan_sql(MW_SQL, parallelism=2)
    assert _kinds(prog) == ["multi_way_join"]
    assert errors_of(validate_program(prog)) == []


MW_TTL_SQL = """
CREATE TABLE nexmark WITH (
  connector = 'nexmark', event_rate = '1000000', num_events = '20000',
  rate_limited = 'false', batch_size = '1024',
  base_time_micros = '1700000000000000'
);
WITH b AS (SELECT bid.auction AS auction, bid.price AS price,
                  bid.bidder AS bidder FROM nexmark
           WHERE bid is not null AND bid.price > 50000000)
SELECT X.auction AS a1, Y.price AS p2, Z.bidder AS b3
FROM b X
JOIN b Y ON X.auction = Y.auction
JOIN b Z ON X.auction = Z.auction
"""


def test_multiway_ttl_mode_equivalence(monkeypatch):
    """TTL-mode (un-windowed) multi-way probe: a 3-way self-cascade
    must plan as one multi_way_join and emit exactly the pairwise
    plan's rows."""
    def run(mw):
        monkeypatch.setenv("ARROYO_MULTIWAY", mw)
        prog = plan_sql(MW_TTL_SQL, parallelism=1)
        clear_sink("results")
        LocalRunner(prog).run()
        rows = sorted(
            (int(b.columns["a1"][i]), float(b.columns["p2"][i]),
             int(b.columns["b3"][i]))
            for b in sink_output("results") for i in range(len(b)))
        return prog, rows

    prog_on, rows_on = run("1")
    prog_off, rows_off = run("0")
    assert _kinds(prog_on) == ["multi_way_join"]
    assert _kinds(prog_off) == ["join_with_expiration",
                                "join_with_expiration"]
    assert rows_on and rows_on == rows_off


def test_multiway_bails_on_different_keys(monkeypatch):
    """A second join on a DIFFERENT key must keep the pairwise plan
    (the rewrite requires one shared key)."""
    monkeypatch.setenv("ARROYO_MULTIWAY", "1")
    sql = """
CREATE TABLE nexmark WITH (
  connector = 'nexmark', event_rate = '1000000', num_events = '2000',
  rate_limited = 'false', batch_size = '512');
WITH b AS (SELECT bid.auction AS auction, bid.bidder AS bidder,
                  bid.price AS price FROM nexmark WHERE bid is not null)
SELECT X.price AS p1, Y.price AS p2, Z.price AS p3
FROM b X
JOIN b Y ON X.auction = Y.auction
JOIN b Z ON X.bidder = Z.bidder
"""
    prog = plan_sql(sql)
    assert _kinds(prog) == ["join_with_expiration", "join_with_expiration"]


# -- checkpoint round-trip with rescale --------------------------------------

RT_SQL = """
CREATE TABLE nexmark WITH (
  connector = 'nexmark', event_rate = '60000', num_events = '{n}',
  rate_limited = 'true', batch_size = '1024',
  base_time_micros = '1700000000000000'
);
CREATE TABLE sinkt (auction BIGINT, price BIGINT, reserve BIGINT) WITH (
  connector = 'single_file', path = '{out}', type = 'sink');
INSERT INTO sinkt
WITH b AS (SELECT bid.auction AS auction, bid.price AS price
           FROM nexmark WHERE bid is not null AND bid.price > 40000000),
     a AS (SELECT auction.id AS id, auction.reserve AS reserve
           FROM nexmark WHERE auction is not null)
SELECT X.auction AS auction, X.price AS price, Y.reserve AS reserve
FROM b X JOIN a Y ON X.auction = Y.id
"""


def _rows_of(path):
    return sorted((r["auction"], r["price"], r["reserve"])
                  for r in (json.loads(line) for line in open(path)))


def test_join_checkpoint_restores_with_rescale(tmp_path, monkeypatch):
    """Headline round-trip (mirrors the q5 chaining test): partitioned
    join state checkpointed mid-stream at parallelism 2 restores at
    parallelism 3 — the snapshot batches re-filter by key range and
    re-partition into fresh sorted runs — with exactly-once output.

    The source is RATE-LIMITED (60k events at 60k/s = a ~1s stream) so
    the barrier injected at t+0.3s deterministically lands mid-stream:
    with the unthrottled source the vectorized ingest drains all 60k
    events in tens of milliseconds on a fast box — the sources are then
    already finished when the barrier arrives, the checkpoint can never
    complete, and the test times out (the "fails at HEAD on loaded
    boxes" flake was actually fails-when-the-run-finishes-too-soon).
    Acceptance is unchanged: exactly-once row equality after a 2 -> 3
    mid-restore rescale."""
    monkeypatch.setenv("ARROYO_JOIN_STATE", "partitioned")
    n = 60_000
    ref_path = tmp_path / "ref.jsonl"
    out_path = tmp_path / "out.jsonl"
    url = f"file://{tmp_path}/ckpt"

    LocalRunner(plan_sql(RT_SQL.format(n=n, out=ref_path),
                         parallelism=2)).run()
    reference = _rows_of(ref_path)
    assert reference

    prog = plan_sql(RT_SQL.format(n=n, out=out_path), parallelism=2)

    async def run_phase1():
        engine = Engine.for_local(prog, "join-rt", checkpoint_url=url)
        running = engine.start()
        await asyncio.sleep(0.3)
        await running.checkpoint(epoch=1, then_stop=True)
        assert await running.wait_for_checkpoint(1, timeout=60)
        try:
            await running.join()
        except RuntimeError:
            pass

    asyncio.run(run_phase1())

    join_id = next(nd.operator_id for nd in prog.nodes()
                   if "join" in nd.operator_id)
    prog.update_parallelism({join_id: 3})

    async def run_phase2():
        engine = Engine.for_local(prog, "join-rt", checkpoint_url=url,
                                  restore_epoch=1)
        running = engine.start()
        await running.join()

    asyncio.run(run_phase2())
    assert _rows_of(out_path) == reference
