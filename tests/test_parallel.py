"""Multi-chip mesh path: MeshKeyedBinState (the engine's sharded window
state, all_to_all re-key over the ("keys",) mesh) against numpy oracles,
overflow/zero-loss pressure, checkpoint rescale, and SQL-level
mesh-vs-single-device equivalence on the q5 pipeline shape."""

import numpy as np
import pytest

from arroyo_tpu.graph.logical import AggKind, AggSpec
from arroyo_tpu.parallel.mesh_window import (
    MeshKeyedBinState,
    make_bin_state,
    mesh_key_shards,
)
from arroyo_tpu.types import hash_columns

SEC = 1_000_000


def oracle_windows(ts, kh, vals, width, slide):
    exp = {}
    for t, k, v in zip(ts.tolist(), kh.tolist(), vals.tolist()):
        e = (t // slide + 1) * slide
        while e - width <= t < e:
            c, s, mn, mx = exp.get((k, e), (0, 0, 1 << 60, -(1 << 60)))
            exp[(k, e)] = (c + 1, s + v, min(mn, v), max(mx, v))
            e += slide
    return exp


AGGS = (AggSpec(AggKind.COUNT, None, "cnt"),
        AggSpec(AggKind.SUM, "v", "total"),
        AggSpec(AggKind.MIN, "v", "lo"),
        AggSpec(AggKind.MAX, "v", "hi"))


def drive(st, kh, ts, vals, batches=3, final=True):
    """Feed rows in batches with interleaved watermark fires; returns the
    accumulated {(key, window_end): (cnt, sum, min, max)} and asserts no
    pane fires twice."""
    got = {}
    bounds = np.linspace(0, len(kh), batches + 1).astype(int)
    outs = []
    for s, e in zip(bounds[:-1], bounds[1:]):
        if e <= s:
            continue
        st._lookup_or_insert(kh[s:e])
        st.update(kh[s:e], ts[s:e], {"v": vals[s:e]})
        f = st.fire_panes(int(ts[e - 1]))
        if f:
            outs.append(f)
    if final:
        f = st.fire_panes(1 << 60, final=True)
        if f:
            outs.append(f)
    for kk, oc, wend, _cnts in outs:
        for j in range(len(kk)):
            key = (int(kk[j]), int(wend[j]))
            assert key not in got, f"pane fired twice: {key}"
            got[key] = (int(oc["cnt"][j]), int(oc["total"][j]),
                        int(oc["lo"][j]), int(oc["hi"][j]))
    return got


@pytest.mark.parametrize("nk,width_s,slide_s", [
    (8, 2, 1), (4, 1, 1), (2, 3, 1), (8, 1, 1)])
def test_mesh_state_matches_oracle(rng, nk, width_s, slide_s):
    import jax

    if len(jax.devices()) < nk:
        pytest.skip("not enough devices")
    n = 4000
    ts = np.sort(rng.integers(0, 8 * SEC, n)).astype(np.int64)
    keys = rng.integers(0, 40, n).astype(np.int64)
    vals = rng.integers(1, 100, n).astype(np.int64)
    kh = hash_columns([keys])
    st = MeshKeyedBinState(AGGS, slide_s * SEC, width_s * SEC,
                           capacity=512, n_shards=nk)
    got = drive(st, kh, ts, vals)
    exp = oracle_windows(ts, kh, vals, width_s * SEC, slide_s * SEC)
    assert got == exp
    assert st.overflow_counters() == (0, 0)


def test_mesh_overflow_pressure_zero_loss(rng):
    """Key cardinality far beyond the initial per-shard capacity, plus
    heavy skew (one hot shard): host admission must grow capacity ahead
    of dispatch — zero rows lost, device counters stay 0."""
    n = 6000
    ts = np.sort(rng.integers(0, 4 * SEC, n)).astype(np.int64)
    # ~3000 distinct keys >> initial per-shard capacity (floored at 64)
    keys = rng.integers(0, 3000, n).astype(np.int64)
    vals = rng.integers(1, 100, n).astype(np.int64)
    kh = hash_columns([keys])
    st = MeshKeyedBinState(AGGS, SEC, 2 * SEC, capacity=64, n_shards=8)
    assert st.C == 64  # the floor — so the assert below is not vacuous
    got = drive(st, kh, ts, vals, batches=5)
    exp = oracle_windows(ts, kh, vals, 2 * SEC, SEC)
    assert got == exp  # every row accounted for
    assert st.overflow_counters() == (0, 0)
    assert st.C > 64  # growth actually happened


def test_mesh_null_skipping(rng):
    """NaN (SQL NULL) rows skip SUM/MIN/MAX and AVG's divisor on the mesh
    path too."""
    n = 600
    ts = np.sort(rng.integers(0, 2 * SEC, n)).astype(np.int64)
    keys = rng.integers(0, 6, n).astype(np.int64)
    vals = rng.integers(1, 100, n).astype(np.float64)
    nulls = rng.random(n) < 0.5
    col = np.where(nulls, np.nan, vals)
    kh = hash_columns([keys])
    aggs = (AggSpec(AggKind.COUNT, "v", "cv"),
            AggSpec(AggKind.AVG, "v", "mean"),
            AggSpec(AggKind.SUM, "v", "total"))
    st = MeshKeyedBinState(aggs, SEC, SEC, capacity=128, n_shards=8)
    st._lookup_or_insert(kh)
    st.update(kh, ts, {"v": col})
    f = st.fire_panes(1 << 60, final=True)
    kk, oc, wend, _ = f
    exp = {}
    for t, k, v, isn in zip(ts.tolist(), kh.tolist(), vals.tolist(),
                            nulls.tolist()):
        e = (t // SEC + 1) * SEC
        c, s = exp.get((k, e), (0, 0.0))
        if not isn:
            exp[(k, e)] = (c + 1, s + v)
        else:
            exp.setdefault((k, e), (c, s))
    for j in range(len(kk)):
        c, s = exp[(int(kk[j]), int(wend[j]))]
        assert int(oc["cv"][j]) == c
        if c == 0:
            assert np.isnan(oc["mean"][j]) and np.isnan(oc["total"][j])
        else:
            assert oc["mean"][j] == pytest.approx(s / c, rel=1e-5)
            assert oc["total"][j] == pytest.approx(s, rel=1e-5)


def test_mesh_snapshot_restore_rescale(rng):
    """Checkpoint on an 8-shard mesh, restore onto 4 shards mid-stream:
    output equals the uninterrupted run (key-range re-shard,
    parquet.rs:194-218 analog)."""
    n = 3000
    ts = np.sort(rng.integers(0, 6 * SEC, n)).astype(np.int64)
    keys = rng.integers(0, 30, n).astype(np.int64)
    vals = rng.integers(1, 100, n).astype(np.int64)
    kh = hash_columns([keys])
    half = n // 2

    st8 = MeshKeyedBinState(AGGS, SEC, 2 * SEC, capacity=256, n_shards=8)
    st8._lookup_or_insert(kh[:half])
    st8.update(kh[:half], ts[:half], {"v": vals[:half]})
    f1 = st8.fire_panes(int(ts[half - 1]))
    snap = st8.snapshot()

    st4 = MeshKeyedBinState(AGGS, SEC, 2 * SEC, capacity=256, n_shards=4)
    st4.restore({k: np.asarray(v) for k, v in snap.items()})
    st4._lookup_or_insert(kh[half:])
    st4.update(kh[half:], ts[half:], {"v": vals[half:]})
    f2 = st4.fire_panes(1 << 60, final=True)

    got = {}
    for f in (f1, f2):
        if f is None:
            continue
        kk, oc, wend, _ = f
        for j in range(len(kk)):
            key = (int(kk[j]), int(wend[j]))
            assert key not in got
            got[key] = (int(oc["cnt"][j]), int(oc["total"][j]),
                        int(oc["lo"][j]), int(oc["hi"][j]))
    exp = oracle_windows(ts, kh, vals, 2 * SEC, SEC)
    assert got == exp


def test_merge_snapshots_min_max_across_disjoint_spans():
    """Rescale-merge two parent snapshots whose bin SPANS differ: the
    merged state must pad each channel with its aggregation identity,
    not 0 — a 0-pad makes MIN (and MAX over negatives) wrongly emit 0
    for windows spanning bins the key's parent never held."""
    from arroyo_tpu.ops.keyed_bins import (KeyedBinState,
                                           merge_canonical_snapshots)

    def fill(keys, ts, vals):
        st = KeyedBinState(AGGS, SEC, 2 * SEC, capacity=64)
        kh = hash_columns([np.asarray(keys, dtype=np.int64)])
        st.update(kh, np.asarray(ts, dtype=np.int64),
                  {"v": np.asarray(vals, dtype=np.int64)})
        return kh, st.snapshot()

    # parent A: key 1 with data in bins 10-11 (all values >= 5)
    kh_a, snap_a = fill([1, 1], [10 * SEC, 11 * SEC], [5, 9])
    # parent B: key 2 with data in bins 12-13 (all values negative)
    kh_b, snap_b = fill([2, 2], [12 * SEC, 13 * SEC], [-7, -3])

    merged = merge_canonical_snapshots(
        {k: np.asarray(v) for k, v in snap_a.items()},
        {k: np.asarray(v) for k, v in snap_b.items()})
    st = KeyedBinState(AGGS, SEC, 2 * SEC, capacity=64)
    st.restore(merged)
    f = st.fire_panes(1 << 60, final=True)
    assert f is not None
    kk, oc, wend, _ = f
    got = {(int(kk[j]), int(wend[j])):
           (int(oc["cnt"][j]), int(oc["total"][j]),
            int(oc["lo"][j]), int(oc["hi"][j]))
           for j in range(len(kk))}
    all_ts = np.array([10 * SEC, 11 * SEC, 12 * SEC, 13 * SEC], np.int64)
    all_kh = np.concatenate([kh_a[:1], kh_a[1:], kh_b[:1], kh_b[1:]])
    all_vals = np.array([5, 9, -7, -3], np.int64)
    exp = oracle_windows(all_ts, all_kh, all_vals, 2 * SEC, SEC)
    assert got == exp


def test_make_bin_state_selects_mesh(monkeypatch):
    import jax

    monkeypatch.setenv("ARROYO_MESH", "auto")
    st = make_bin_state(AGGS, SEC, 2 * SEC)
    if len(jax.devices()) > 1:
        assert isinstance(st, MeshKeyedBinState)
        assert st.nk == mesh_key_shards()
    monkeypatch.setenv("ARROYO_MESH", "off")
    from arroyo_tpu.ops.keyed_bins import KeyedBinState

    assert isinstance(make_bin_state(AGGS, SEC, 2 * SEC), KeyedBinState)


Q5_SHAPE = """
WITH bids as (SELECT k as auction, ts_col as datetime FROM events)
SELECT B1.auction, HOP(INTERVAL '1' SECOND, INTERVAL '2' SECOND)
       as window, count(*) AS num
FROM bids B1 GROUP BY 1, 2
"""


def _run_sql_q5(monkeypatch, mesh: str):
    """Run a q5-shaped hop aggregate through the REAL SQL->planner->engine
    path with the mesh forced on/off; returns sorted output tuples."""
    from arroyo_tpu import Batch
    from arroyo_tpu.connectors.memory import clear_sink, sink_output
    from arroyo_tpu.engine.engine import LocalRunner
    from arroyo_tpu.sql import SchemaProvider, plan_sql

    monkeypatch.setenv("ARROYO_MESH", mesh)
    rng = np.random.default_rng(11)
    n = 3000
    ts = np.sort(rng.integers(0, 5 * SEC, n)).astype(np.int64)
    p = SchemaProvider()
    p.add_memory_table("events", {"k": "i", "ts_col": "t"}, [
        Batch(ts, {"k": rng.integers(0, 25, n).astype(np.int64),
                   "ts_col": ts.copy()})])
    clear_sink("results")
    prog = plan_sql(
        "CREATE TABLE out WITH (connector='memory', name='results');"
        "INSERT INTO out " + Q5_SHAPE, p)
    LocalRunner(prog).run()
    out = Batch.concat(sink_output("results"))
    return sorted(zip(out.columns["auction"].tolist(),
                      out.columns["window_end"].tolist(),
                      out.columns["num"].tolist()))


def test_sql_q5_mesh_matches_single_device(monkeypatch):
    """The q5 SQL pipeline (not a bespoke demo) on the 8-device mesh
    produces exactly the single-device output (VERDICT round-1 item #2)."""
    import jax

    if len(jax.devices()) < 2:
        pytest.skip("needs a multi-device mesh")
    mesh_out = _run_sql_q5(monkeypatch, "auto")
    single_out = _run_sql_q5(monkeypatch, "off")
    assert mesh_out == single_out
    assert len(mesh_out) > 0


def test_snapshot_cross_topology(rng):
    """Checkpoints are topology-independent: a mesh snapshot restores into
    the single-device KeyedBinState and vice versa, with identical
    continued output (the deployment may lose or gain chips between
    runs)."""
    from arroyo_tpu.ops.keyed_bins import KeyedBinState

    n = 2000
    ts = np.sort(rng.integers(0, 6 * SEC, n)).astype(np.int64)
    keys = rng.integers(0, 20, n).astype(np.int64)
    vals = rng.integers(1, 100, n).astype(np.int64)
    kh = hash_columns([keys])
    half = n // 2
    exp = oracle_windows(ts, kh, vals, 2 * SEC, SEC)

    for first_cls, second_cls in [
            (lambda: MeshKeyedBinState(AGGS, SEC, 2 * SEC, capacity=128,
                                       n_shards=8),
             lambda: KeyedBinState(AGGS, SEC, 2 * SEC, capacity=128)),
            (lambda: KeyedBinState(AGGS, SEC, 2 * SEC, capacity=128),
             lambda: MeshKeyedBinState(AGGS, SEC, 2 * SEC, capacity=128,
                                       n_shards=4))]:
        st1 = first_cls()
        st1._lookup_or_insert(kh[:half])
        st1.update(kh[:half], ts[:half], {"v": vals[:half]})
        f1 = st1.fire_panes(int(ts[half - 1]))
        snap = {k: np.asarray(v) for k, v in st1.snapshot().items()}

        st2 = second_cls()
        st2.restore(snap)
        st2._lookup_or_insert(kh[half:])
        st2.update(kh[half:], ts[half:], {"v": vals[half:]})
        f2 = st2.fire_panes(1 << 60, final=True)

        got = {}
        for f in (f1, f2):
            if f is None:
                continue
            kk, oc, wend, _ = f
            for j in range(len(kk)):
                key = (int(kk[j]), int(wend[j]))
                assert key not in got
                got[key] = (int(oc["cnt"][j]), int(oc["total"][j]),
                            int(oc["lo"][j]), int(oc["hi"][j]))
        assert got == exp, (type(st1).__name__, type(st2).__name__)


def test_mesh_out_of_order_before_fire(rng):
    """Rows older than the first batch (but with no pane fired yet) are
    live and must aggregate — the base is the late-row threshold derived
    from fired panes, never the first batch's minimum bin."""
    st = MeshKeyedBinState(AGGS, SEC, 2 * SEC, capacity=64, n_shards=4)
    kh = hash_columns([np.array([7, 7, 7], dtype=np.int64)])
    # batch 1 at t=10s; batch 2 arrives out of order at t=2s
    st._lookup_or_insert(kh[:1])
    st.update(kh[:1], np.array([10 * SEC], np.int64), {"v": np.array([5])})
    st._lookup_or_insert(kh[1:2])
    st.update(kh[1:2], np.array([2 * SEC], np.int64), {"v": np.array([9])})
    f = st.fire_panes(1 << 60, final=True)
    kk, oc, wend, _ = f
    got = {int(w): (int(c), int(t)) for w, c, t in
           zip(wend, oc["cnt"], oc["total"])}
    # t=2s feeds windows ending 3s and 4s; t=10s feeds 11s and 12s
    assert got == {3 * SEC: (1, 9), 4 * SEC: (1, 9),
                   11 * SEC: (1, 5), 12 * SEC: (1, 5)}
    assert st.late_rows == 0


def _run_sql_q8_shape(monkeypatch, mesh: str):
    """q8-shaped windowed join (two tumbling counts joined per window)
    through the SQL engine with the mesh forced on/off."""
    from arroyo_tpu import Batch
    from arroyo_tpu.connectors.memory import clear_sink, sink_output
    from arroyo_tpu.engine.engine import LocalRunner
    from arroyo_tpu.sql import SchemaProvider, plan_sql

    monkeypatch.setenv("ARROYO_MESH", mesh)
    rng = np.random.default_rng(23)
    n = 2000
    ts = np.sort(rng.integers(0, 4 * SEC, n)).astype(np.int64)
    p = SchemaProvider()
    p.add_memory_table("ev", {"u": "i", "s": "i"}, [
        Batch(ts, {"u": rng.integers(0, 12, n).astype(np.int64),
                   "s": rng.integers(0, 12, n).astype(np.int64)})])
    clear_sink("results")
    prog = plan_sql("""
      SELECT P.u as u, P.np as np, A.na as na
      FROM (
        SELECT u, TUMBLE(INTERVAL '1' SECOND) as window, count(*) as np
        FROM ev GROUP BY 1, 2
      ) AS P
      JOIN (
        SELECT s, TUMBLE(INTERVAL '1' SECOND) as window, count(*) as na
        FROM ev GROUP BY 1, 2
      ) AS A
      ON P.u = A.s and P.window = A.window
    """, p)
    LocalRunner(prog).run()
    out = Batch.concat(sink_output("results"))
    return sorted(zip(out.columns["u"].tolist(),
                      out.columns["np"].tolist(),
                      out.columns["na"].tolist()))


def test_sql_q8_join_mesh_matches_single_device(monkeypatch):
    """The q8-shaped join pipeline: both tumbling-count inputs run with
    mesh-sharded state; the joined output must match single-device exactly."""
    import jax

    if len(jax.devices()) < 2:
        pytest.skip("needs a multi-device mesh")
    mesh_out = _run_sql_q8_shape(monkeypatch, "auto")
    single_out = _run_sql_q8_shape(monkeypatch, "off")
    assert mesh_out == single_out
    assert len(mesh_out) > 0


def test_route_shift_spreads_subtask_key_slice(rng):
    """At operator parallelism P > 1 each subtask only sees a 1/P slice
    of the TOP key-hash bits (subtask key ranges).  Routing on those
    same bits funnels the whole slice onto one shard; set_route_shift
    skips them so the mesh spreads — with identical window output."""
    n = 3000
    ts = np.sort(rng.integers(0, 6 * SEC, n)).astype(np.int64)
    keys = rng.integers(0, 60, n).astype(np.int64)
    vals = rng.integers(1, 100, n).astype(np.int64)
    kh = hash_columns([keys])
    # restrict keys to subtask 3-of-4's range: fixed top 2 bits (0b11)
    kh = (kh >> np.uint64(2)) | (np.uint64(3) << np.uint64(62))

    plain = MeshKeyedBinState(AGGS, SEC, 2 * SEC, capacity=256, n_shards=4)
    plain._lookup_or_insert(kh)
    assert (plain.shard_counts > 0).sum() == 1, \
        "without the shift, a top-bit key slice must funnel (the bug)"

    st = MeshKeyedBinState(AGGS, SEC, 2 * SEC, capacity=256, n_shards=4)
    st.set_route_shift(2)
    got = drive(st, kh, ts, vals)
    assert got == oracle_windows(ts, kh, vals, 2 * SEC, SEC)
    assert st.overflow_counters() == (0, 0)
    assert (st.shard_counts > 0).sum() > 1, \
        "route shift must spread the slice across shards"


def test_binagg_sets_route_shift_at_parallelism(run_async):
    """BinAggOperator wires the shift from its subtask parallelism
    before any state lands (the satellite fix: parallelism > 1 no
    longer silently degenerates the mesh to one device per subtask)."""
    from arroyo_tpu.engine.context import Context
    from arroyo_tpu.engine.operators_window import BinAggOperator
    from arroyo_tpu.types import TaskInfo

    async def scenario(par):
        ti = TaskInfo("job", "agg-0", "agg", 1 % par, par)
        ctx, _q = Context.new_for_test(ti)
        op = BinAggOperator("agg", 2 * SEC, SEC,
                            (AggSpec(AggKind.COUNT, None, "cnt"),))
        await op.on_start(ctx)
        return op.state

    st = run_async(scenario(4))
    if isinstance(st, MeshKeyedBinState):
        assert st.route_shift == 2
    st1 = run_async(scenario(1))
    if isinstance(st1, MeshKeyedBinState):
        assert st1.route_shift == 0


def test_mesh_engages_under_default_bench_config():
    """Regression (ISSUE 11 satellite): the default bench config —
    parallelism 1 (bench_parallelism()'s default), ARROYO_MESH unset —
    must place q5's keyed window stages on the mesh when a multi-device
    backend is available.  Mesh width and reshard counters now also
    land in the bench JSON line so a silent fallback is visible."""
    from arroyo_tpu.engine.build import build_operator
    from arroyo_tpu.sql import plan_sql

    prog = plan_sql("""
    CREATE TABLE nexmark WITH (
      connector = 'nexmark', event_rate = '1000000', num_events = '1000',
      rate_limited = 'false', batch_size = '512'
    );
    SELECT bid.auction as auction,
           HOP(INTERVAL '2' SECOND, INTERVAL '10' SECOND) as window,
           count(*) AS num
    FROM nexmark WHERE bid is not null GROUP BY 1, 2
    """, parallelism=1)  # bench_parallelism() default
    agg = next(nd for nd in prog.nodes()
               if "aggregator" in nd.operator_id)
    op = build_operator(agg.operator)
    assert isinstance(op.state, MeshKeyedBinState), type(op.state)
    assert op.state.nk == mesh_key_shards() == 8


MESH_RT_SQL = """
CREATE TABLE nexmark WITH (
  connector = 'nexmark', event_rate = '{rate}', num_events = '{n}',
  rate_limited = '{limited}', batch_size = '1024',
  base_time_micros = '1700000000000000'
);
CREATE TABLE sinkt (auction BIGINT, num BIGINT) WITH (
  connector = 'single_file', path = '{out}', type = 'sink');
INSERT INTO sinkt
WITH bids as (SELECT bid.auction as auction, bid.datetime as datetime
    FROM nexmark where bid is not null)
SELECT B1.auction as auction, count(*) AS num
FROM bids B1
GROUP BY 1, HOP(INTERVAL '2' SECOND, INTERVAL '10' SECOND)
"""


def _mesh_rt_rows(path):
    import json

    return sorted((r["auction"], r["num"])
                  for r in map(json.loads, open(path)))


@pytest.mark.parametrize("first,second", [
    pytest.param("2", "4", marks=pytest.mark.slow), ("4", "off"),
    pytest.param("off", "2", marks=pytest.mark.slow)])
def test_mesh_checkpoint_interchange_engine_roundtrip(
        tmp_path, monkeypatch, first, second):
    """Mesh-state checkpoint interchange through the REAL engine
    (mirrors the q5 chaining round-trip): snapshot at one mesh width,
    restore at another (2->4, 4->off, off->2), exactly-once output
    pinned against an uninterrupted reference."""
    import asyncio
    import json  # noqa: F401

    from arroyo_tpu.engine.engine import Engine, LocalRunner
    from arroyo_tpu.sql import plan_sql

    n = 120_000
    ref_path = tmp_path / "ref.jsonl"
    out_path = tmp_path / "out.jsonl"
    url = f"file://{tmp_path}/ckpt"

    # every run is RATE-LIMITED (~1.2s of stream) so the mid-stream
    # barrier lands deterministically — the vectorized ingest path
    # otherwise finishes 120k events before any sleep-then-checkpoint
    # can race it.  The reference uses the SAME source config: nexmark
    # event times derive from the rate schedule, so configs must match
    # for row equivalence.
    monkeypatch.setenv("ARROYO_MESH", "off")
    LocalRunner(plan_sql(MESH_RT_SQL.format(
        n=n, out=ref_path, rate=100_000, limited="true"))).run()
    reference = _mesh_rt_rows(ref_path)
    assert reference

    monkeypatch.setenv("ARROYO_MESH", first)
    prog = plan_sql(MESH_RT_SQL.format(n=n, out=out_path,
                                       rate=100_000, limited="true"))

    async def run_phase1():
        engine = Engine.for_local(prog, "mesh-rt", checkpoint_url=url)
        running = engine.start()
        await asyncio.sleep(0.4)
        await running.checkpoint(epoch=1, then_stop=True)
        assert await running.wait_for_checkpoint(1, timeout=60)
        try:
            await running.join()
        except RuntimeError:
            pass

    asyncio.run(run_phase1())

    monkeypatch.setenv("ARROYO_MESH", second)

    async def run_phase2():
        engine = Engine.for_local(prog, "mesh-rt", checkpoint_url=url,
                                  restore_epoch=1)
        await engine.start().join()

    asyncio.run(run_phase2())
    assert _mesh_rt_rows(out_path) == reference


def test_ring_pane_aggregate_matches_numpy(rng):
    """Bin-dimension ring parallelism (SURVEY §5 sequence-parallel
    discipline): sliding pane aggregates over an 8-shard bin ring match
    the numpy oracle, for halo widths below, at, and beyond one shard
    block (multiple ppermute rotations)."""
    from arroyo_tpu.parallel.ring_panes import ring_pane_aggregate

    n, shards = 256, 8  # Bl = 32
    vals = rng.integers(-50, 100, n).astype(np.float64)

    def oracle(kind, W):
        out = np.empty(n)
        for t in range(n):
            lo = max(t - W + 1, 0)
            seg = vals[lo:t + 1]
            out[t] = (seg.sum() if kind == "sum" else
                      seg.min() if kind == "min" else seg.max())
        return out

    for W in (1, 7, 32, 33, 100, 256):  # crossing 1, 2, and 4+ shards
        got = ring_pane_aggregate(vals, W, "sum", shards)
        np.testing.assert_allclose(got, oracle("sum", W), rtol=1e-12)
    for kind in ("min", "max"):
        for W in (7, 33, 100):
            got = ring_pane_aggregate(vals, W, kind, shards)
            np.testing.assert_allclose(got, oracle(kind, W))


def test_ring_emission_matches_oracle_long_window(rng, monkeypatch):
    """Long-window (W=100) pane emission through the bin-sharded ring
    kernels (KeyedBinState._emit_ring) matches the pane oracle across
    batched updates, interleaved fires, and eviction."""
    from arroyo_tpu.ops.keyed_bins import KeyedBinState

    monkeypatch.setenv("ARROYO_RING", "on")
    n = 2000
    ts = np.sort(rng.integers(0, 400 * SEC, n)).astype(np.int64)
    keys = rng.integers(0, 15, n).astype(np.int64)
    vals = rng.integers(-50, 100, n).astype(np.int64)
    kh = hash_columns([keys])
    st = KeyedBinState(AGGS, SEC, 100 * SEC, capacity=64)
    assert st._use_ring()
    got = drive(st, kh, ts, vals, batches=5)
    exp = oracle_windows(ts, kh, vals, 100 * SEC, SEC)
    assert got == exp


def test_make_bin_state_selects_ring_shape_for_long_windows(monkeypatch):
    """HOP(1s, 300s)-style shapes route to the ring-capable state even
    when a key mesh is available (bin-dim beats key-dim sharding there)."""
    import jax

    from arroyo_tpu.ops.keyed_bins import KeyedBinState

    monkeypatch.setenv("ARROYO_MESH", "auto")
    st = make_bin_state(AGGS, SEC, 300 * SEC)
    assert isinstance(st, KeyedBinState)
    if len(jax.devices()) > 1:
        assert st._use_ring()
    # short windows on a mesh still take the key-sharded state
    st2 = make_bin_state(AGGS, SEC, 2 * SEC)
    if len(jax.devices()) > 1 and jax.config.jax_enable_x64:
        assert isinstance(st2, MeshKeyedBinState)


def test_sql_hop_long_window_through_ring(rng, monkeypatch):
    """A HOP(1s, 300s) query runs end-to-end through the SQL engine with
    ring-pane emission, with per-(key, window) oracle parity — the
    SQL-reachable proof the ring path is engine-wired, not a demo."""
    import collections

    from arroyo_tpu import Batch
    from arroyo_tpu.connectors.memory import clear_sink, sink_output
    from arroyo_tpu.engine.engine import LocalRunner
    from arroyo_tpu.sql import SchemaProvider, plan_sql

    monkeypatch.setenv("ARROYO_RING", "on")
    n = 400
    ts = np.sort(rng.integers(0, 600 * SEC, n)).astype(np.int64)
    keys = rng.integers(0, 5, n).astype(np.int64)
    p = SchemaProvider()
    p.add_memory_table("events", {"k": "i"}, [Batch(ts, {"k": keys})])
    clear_sink("results")
    LocalRunner(plan_sql(
        "CREATE TABLE out WITH (connector='memory', name='results');"
        "INSERT INTO out SELECT k, HOP(INTERVAL '1' SECOND, INTERVAL"
        " '300' SECOND) as window, count(*) as num "
        "FROM events GROUP BY 1, 2", p)).run()
    out = Batch.concat(sink_output("results"))
    exp = collections.Counter()
    for t, kk in zip(ts.tolist(), keys.tolist()):
        e = (t // SEC + 1) * SEC
        for w in range(300):
            exp[(kk, e + w * SEC)] += 1
    got = {}
    for j in range(len(out)):
        key = (int(out.columns["k"][j]), int(out.columns["window_end"][j]))
        assert key not in got, f"pane emitted twice: {key}"
        got[key] = int(out.columns["num"][j])
    assert got == dict(exp)


def test_mesh_i32_counts_plane_promotes_to_i64(rng, monkeypatch):
    """The mesh state mirrors KeyedBinState's i32 -> i64 counts-plane
    promotion: once total ingested rows could wrap an i32 cell or pane
    sum, d_counts promotes (and the promotion survives a checkpoint
    round-trip) — otherwise COUNT wraps negative and _fire_step's
    cnts > 0 mask silently drops rows (code-review r4 finding)."""
    import jax
    import jax.numpy as jnp

    from arroyo_tpu.ops.keyed_bins import KeyedBinState

    if len(jax.devices()) < 4:
        pytest.skip("not enough devices")
    monkeypatch.setattr(KeyedBinState, "_i32_promote", 500)
    st = MeshKeyedBinState(AGGS, SEC, 2 * SEC, capacity=64, n_shards=4)
    n = 300
    total = 0
    for _ in range(3):
        ts = np.sort(rng.integers(0, 3 * SEC, n)).astype(np.int64)
        keys = rng.integers(0, 10, n).astype(np.int64)
        vals = rng.integers(1, 50, n).astype(np.int64)
        st.update(hash_columns([keys]), ts, {"v": vals})
        total += n
    assert st.d_counts.dtype == jnp.int64
    # round-trip: a promoted snapshot restores promoted (no i32 recast)
    st2 = MeshKeyedBinState(AGGS, SEC, 2 * SEC, capacity=64, n_shards=4)
    st2.restore(st.snapshot())
    assert st2.total_rows == total
    assert st2.d_counts.dtype == jnp.int64
    r = st2.fire_panes(10 ** 9, final=True)
    assert r is not None
    _, cols, _, cnts = r
    assert int(cols["cnt"].sum()) == 2 * total  # W=2 panes, nothing lost
    assert (cnts > 0).all()
