"""Multi-chip SPMD path: shard_map step over the virtual 8-device CPU mesh,
checked against a numpy oracle (route -> bin -> window-sum)."""

import numpy as np
import pytest

from arroyo_tpu.parallel.mesh import make_mesh
from arroyo_tpu.parallel.spmd_window import (
    SpmdWindowEngine,
    SpmdWindowState,
    make_example_rows,
    _split_u64,
)


def oracle(kh, bins, vals, wm_bin, W):
    """Expected per-(key, pane) sums/counts for pane ends <= wm_bin."""
    out = {}
    for k, b, v in zip(kh.tolist(), bins.tolist(), vals.tolist()):
        for pane in range(b, b + W):
            if pane <= wm_bin:
                c, s = out.get((k, pane), (0, 0.0))
                out[(k, pane)] = (c + 1, s + v)
    return out


@pytest.mark.parametrize("source,keys", [(1, 8), (2, 4), (1, 1)])
def test_spmd_step_matches_oracle(source, keys):
    import jax

    if len(jax.devices()) < source * keys:
        pytest.skip("not enough devices")
    mesh = make_mesh(source * keys, source=source, keys=keys)
    W = 3
    eng = SpmdWindowEngine(mesh, n_aggs=1, capacity=512, n_bins=8,
                           window_bins=W, rows_per_shard=256)
    state = eng.init_state()
    step = eng.build_step()

    rng = np.random.default_rng(3)
    n = 256 * source
    kh = (rng.integers(0, 1 << 20, n, dtype=np.uint64)
          * np.uint64(0x9E3779B97F4A7C15))  # spread over u64 space
    lo, hi = _split_u64(kh)
    bins = rng.integers(0, 4, n).astype(np.int32)
    vals = rng.random(n).astype(np.float32)

    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    def put(x, spec):
        return jax.device_put(jnp.asarray(x), NamedSharding(mesh, spec))

    rows = {
        "key_lo": put(lo, P(("source", "keys"))),
        "key_hi": put(hi, P(("source", "keys"))),
        "bin_idx": put(bins, P(("source", "keys"))),
        "values": put(vals[None, :], P(None, ("source", "keys"))),
        "valid": put(np.ones(n, bool), P(("source", "keys"))),
    }
    wm_bin = 5
    state2, emitted = step(state, rows, wm_bin)

    expected = oracle(kh, bins, vals, wm_bin, W)

    mask = np.asarray(emitted["mask"])  # [C_total, B]
    counts = np.asarray(emitted["counts"])
    sums = np.asarray(emitted["aggs"])[0]
    keys_lo = np.asarray(state2.keys).reshape(-1)
    keys_hi = np.asarray(state2.keys_hi).reshape(-1)

    got = {}
    for ci, pane in zip(*np.nonzero(mask)):
        k = (int(keys_hi[ci]) << 32) | int(keys_lo[ci])
        got[(k, int(pane))] = (int(counts[ci, pane]),
                               float(sums[ci, pane]))

    assert set(got) == set(expected), (
        f"missing={list(set(expected) - set(got))[:5]} "
        f"extra={list(set(got) - set(expected))[:5]}")
    for key in expected:
        ec, es = expected[key]
        gc, gs = got[key]
        assert gc == ec, f"count mismatch at {key}: {gc} != {ec}"
        np.testing.assert_allclose(gs, es, rtol=1e-5)


def test_spmd_state_carries_across_steps():
    import jax

    mesh = make_mesh(4, source=1, keys=4)
    eng = SpmdWindowEngine(mesh, n_aggs=1, capacity=256, n_bins=8,
                           window_bins=2, rows_per_shard=128)
    state = eng.init_state()
    step = eng.build_step()
    rows = make_example_rows(128, 1, 1, mesh, seed=1)
    # first step: no watermark -> nothing fires
    state, e1 = step(state, rows, -1)
    assert not np.asarray(e1["mask"]).any()
    # second step: watermark passes all bins -> panes fire incl. step-1 rows
    state, e2 = step(state, rows, 10)
    m = np.asarray(e2["mask"])
    assert m.any()
    # every fired count is even (same rows twice)
    cnts = np.asarray(e2["counts"])[m]
    assert np.all(cnts % 2 == 0)
