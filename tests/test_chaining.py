"""Operator chaining + adaptive batch coalescing (PR 4).

Covers: the chaining pass's fuse/break rules, chain-off topology parity
(ARROYO_CHAIN=0 bit-for-bit), chain-on output equivalence with fewer
tasks, per-member flight-recorder attribution, jitted expression fusion
reducing kernel dispatches, chain-aware rescale override expansion, the
coalescer's boundary behavior (target/linger/schema-change/watermark
ordering), and the headline round-trip: an UN-chained checkpoint of a
Nexmark q5 plan restored CHAINED at a different parallelism with
exactly-once output."""

import asyncio
import json
import time

import numpy as np
import pytest

from arroyo_tpu import AggKind, AggSpec, Batch, Stream
from arroyo_tpu.connectors.memory import clear_sink, sink_output
from arroyo_tpu.engine.coalesce import BatchCoalescer
from arroyo_tpu.engine.engine import Engine, LocalRunner
from arroyo_tpu.graph.chaining import (
    chain_annotations,
    expand_overrides,
    plan_chains,
    validate_chain_plan,
)
from arroyo_tpu.types import StopMode

SEC = 1_000_000


def _map_filter_prog(sink_name, n=2000):
    return (
        Stream.source("impulse", {"event_rate": 0.0, "message_count": n,
                                  "batch_size": 128})
        .map(lambda c: {"counter": c["counter"],
                        "doubled": c["counter"] * 2}, name="double")
        .map(lambda c: {"counter": c["counter"],
                        "tripled": c["doubled"] + c["counter"]},
             name="triple")
        .filter(lambda c: c["tripled"] % 2 == 0, name="evens")
        .sink("memory", {"name": sink_name})
    )


# -- the planning pass -------------------------------------------------------


def test_plan_chains_fuse_and_break_rules():
    prog = (
        Stream.source("impulse", {"event_rate": 0.0, "message_count": 10},
                      parallelism=2)
        .map(lambda c: {"counter": c["counter"], "b": c["counter"] % 3},
             name="m1", )
        .map(lambda c: dict(c), name="m2")
        .key_by("b")
        .count()  # SHUFFLE edge in: breaks the chain
        .sink("memory", {"name": "pc"}, parallelism=1)
    )
    plan = plan_chains(prog)
    validate_chain_plan(prog, plan)
    assert len(plan.groups) == 1
    kinds = [prog.node(m).operator.kind.value for m in plan.groups[0]]
    # source and sink never chain; the shuffle into count breaks it
    assert kinds == ["expression", "expression", "key_by"]
    heads = chain_annotations(prog)
    assert set(heads.values()) == {plan.groups[0][0]}


def test_plan_chains_breaks_on_parallelism_change():
    from arroyo_tpu.graph.logical import (ColumnExpr, LogicalOperator,
                                          OpKind)

    s = Stream.source("impulse", {"event_rate": 0.0, "message_count": 10},
                      parallelism=2)
    m = s.map(lambda c: dict(c), name="m1")
    m2 = m._chain(
        LogicalOperator(OpKind.EXPRESSION, "m2",
                        expr=ColumnExpr("m2", lambda c: dict(c))),
        parallelism=4)  # rebalance edge: must not chain across it
    m3 = m2.map(lambda c: dict(c), name="m3")
    m3.sink("memory", {"name": "pf"})
    plan = plan_chains(m.program)
    validate_chain_plan(m.program, plan)
    for grp in plan.groups:
        pars = {m.program.node(x).parallelism for x in grp}
        assert len(pars) == 1
    # m1 (p=2) never groups with m2 (p=4); m2+m3 (both p=4) do
    assert any(len(grp) == 2 for grp in plan.groups)


def test_chain_disabled_is_empty_plan(monkeypatch):
    monkeypatch.setenv("ARROYO_CHAIN", "0")
    prog = _map_filter_prog("off-plan")
    plan = plan_chains(prog)
    assert not plan.groups and not plan.head_of
    assert chain_annotations(prog) == {}


def test_expand_overrides_addresses_whole_chain():
    prog = (
        Stream.source("impulse", {"event_rate": 0.0, "message_count": 10},
                      parallelism=2)
        .map(lambda c: dict(c), name="m1")
        .map(lambda c: dict(c), name="m2")
        .key_by("counter")
        .count()
        .sink("memory", {"name": "eo"}, parallelism=1)
    )
    plan = plan_chains(prog)
    (chain,) = plan.groups
    out = expand_overrides(prog, {chain[1]: 6})
    # the override lands on every member of the chain, nothing else
    assert out == {m: 6 for m in chain}
    # max_parallelism of ANY member caps the whole chain
    prog.node(chain[0]).max_parallelism = 3
    out = expand_overrides(prog, {chain[1]: 6})
    assert out == {m: 3 for m in chain}
    # unchained operators pass through untouched
    count_id = next(n.operator_id for n in prog.nodes()
                    if n.operator_id.endswith("_count"))
    assert expand_overrides(prog, {count_id: 2}) == {count_id: 2}


# -- topology + equivalence --------------------------------------------------


def _run_engine(prog, job_id):
    async def scenario():
        engine = Engine.for_local(prog, job_id)
        running = engine.start()
        await running.join()
        return engine

    return asyncio.run(scenario())


def test_chain_off_reproduces_per_operator_topology(monkeypatch):
    """ARROYO_CHAIN=0: one task per logical operator subtask, singleton
    member lists — today's topology bit-for-bit."""
    monkeypatch.setenv("ARROYO_CHAIN", "0")
    clear_sink("topo-off")
    prog = _map_filter_prog("topo-off")
    engine = _run_engine(prog, "topo-off-job")
    n_ops = len(prog.nodes())
    assert len(engine.subtasks) == n_ops == 5
    for (op_id, _), h in engine.subtasks.items():
        assert h.member_ids == [op_id]
        assert h.task_info.operator_id == op_id


def test_chain_on_equivalent_output_fewer_tasks(monkeypatch):
    monkeypatch.setenv("ARROYO_CHAIN", "0")
    clear_sink("eq-off")
    off_engine = _run_engine(_map_filter_prog("eq-off"), "eq-off-job")
    monkeypatch.setenv("ARROYO_CHAIN", "1")
    clear_sink("eq-on")
    on_engine = _run_engine(_map_filter_prog("eq-on"), "eq-on-job")

    rows_off = Batch.concat(sink_output("eq-off"))
    rows_on = Batch.concat(sink_output("eq-on"))
    assert sorted(rows_on.columns["counter"].tolist()) == \
        sorted(rows_off.columns["counter"].tolist())
    np.testing.assert_array_equal(
        np.sort(rows_on.columns["tripled"]),
        np.sort(rows_off.columns["tripled"]))
    # map+map+filter collapsed into one task: 3 runners instead of 5
    assert len(on_engine.subtasks) == 3 < len(off_engine.subtasks)
    chained = next(h for h in on_engine.subtasks.values()
                   if len(h.member_ids) > 1)
    assert len(chained.member_ids) == 3


def test_chained_members_keep_flight_recorder_attribution(monkeypatch):
    """Rollups still attribute per-member kernel-seconds / message
    counts after fusion — the autoscaler's policy input is unchanged.
    Pinned to the jitted composed-expr mode: the host ingest spine
    (tested separately below) dispatches no kernels at all."""
    from arroyo_tpu.obs.metrics import job_operator_summary

    monkeypatch.setenv("ARROYO_CHAIN", "1")
    monkeypatch.setenv("ARROYO_CHAIN_FUSE_INGEST", "0")
    clear_sink("attr")
    prog = _map_filter_prog("attr", n=4000)
    engine = _run_engine(prog, "attr-job")
    chained = next(h for h in engine.subtasks.values()
                   if len(h.member_ids) > 1)
    summary = job_operator_summary("attr-job")
    for m in chained.member_ids:
        assert m in summary, f"member {m} missing from rollup"
        assert summary[m].get("messages_recv_total", 0) >= 4000
        # event-time lag is observed per member, fused or not — the
        # autoscaler's lag signal stays per-operator
        assert summary[m].get("event_time_lag_seconds_count", 0) > 0
    # batch latency + kernel time attribute to each execution step's
    # FIRST member (a fused expression run is one dispatch); the two
    # step entries here are the fused double+triple head and the filter
    head = chained.member_ids[0]
    tail = chained.member_ids[-1]
    assert summary[head].get("batch_processing_seconds_count", 0) > 0
    assert summary[tail].get("batch_processing_seconds_count", 0) > 0
    assert summary[head].get("kernel_seconds_total", 0) > 0


def test_expression_fusion_reduces_dispatches(monkeypatch):
    """map→map→(filter) chains jit-compose: fewer kernel dispatches per
    run than the unchained topology over identical data.  Coalescing is
    pinned OFF: with it on, both topologies collapse to a handful of
    merged batches and the margin shrinks to ±1 dispatch — one stray
    async dispatch from a neighboring test then flips the comparison
    (observed flake at (6, 5))."""
    from arroyo_tpu.obs import perf

    monkeypatch.setenv("ARROYO_COALESCE", "0")

    def dispatches(chain):
        monkeypatch.setenv("ARROYO_CHAIN", chain)
        sink = f"disp-{chain}"
        clear_sink(sink)
        prog = _map_filter_prog(sink, n=8000)
        before = perf.counter("kernel_dispatches")
        _run_engine(prog, f"disp-job-{chain}")
        return perf.counter("kernel_dispatches") - before

    d_off = dispatches("0")
    d_on = dispatches("1")
    assert d_on < d_off, (d_on, d_off)


def test_chained_checkpoint_reports_every_member(monkeypatch):
    """One checkpoint_completed per (member operator, subtask): the
    controller's epoch tracker sees the same completions as unchained."""
    monkeypatch.setenv("ARROYO_CHAIN", "1")
    clear_sink("ckptm")

    async def scenario():
        prog = (
            Stream.source("impulse", {"event_rate": 5_000.0,
                                      "message_count": 2000,
                                      "batch_size": 100})
            .map(lambda c: {"counter": c["counter"]}, name="ident")
            .map(lambda c: {"counter": c["counter"] + 0}, name="ident2")
            .sink("memory", {"name": "ckptm"})
        )
        engine = Engine.for_local(prog, "ckptm-job")
        running = engine.start()
        await asyncio.sleep(0.05)
        await running.checkpoint(epoch=1)
        assert await running.wait_for_checkpoint(1)
        resps = await running.join()
        return prog, engine, resps

    prog, engine, resps = asyncio.run(scenario())
    assert len(engine.subtasks) == 3  # source, chain(ident,ident2), sink
    completed = {(r.operator_id, r.task_index) for r in resps
                 if r.kind == "checkpoint_completed"
                 and r.subtask_metadata.epoch == 1}
    expected = {(n.operator_id, 0) for n in prog.nodes()}
    assert completed == expected  # 4 member completions from 3 runners
    out = Batch.concat(sink_output("ckptm"))
    assert len(out) == 2000


# -- coalescer ---------------------------------------------------------------


def _batch(vals, ts0=1000):
    v = np.asarray(vals, dtype=np.int64)
    return Batch(np.arange(ts0, ts0 + len(v), dtype=np.int64), {"v": v})


def test_coalescer_target_and_passthrough():
    c = BatchCoalescer(target=10, linger_secs=60.0)
    assert c.add(0, _batch([])) == []  # empty: nothing buffered
    assert not c.pending
    # singleton below target buffers; deadline armed
    assert c.add(0, _batch([1, 2, 3])) == []
    assert c.pending and c.deadline is not None
    # crossing the target releases ONE merged batch
    out = c.add(0, _batch([4, 5, 6, 7, 8, 9, 10]))
    assert len(out) == 1
    side, merged = out[0]
    assert side == 0 and len(merged) == 10
    assert merged.columns["v"].tolist() == list(range(1, 11))
    assert not c.pending and c.deadline is None
    # a batch already >= target passes straight through, unmerged
    big = _batch(list(range(20)))
    out = c.add(1, big)
    assert out == [(1, big)]


def test_coalescer_schema_change_flushes_in_order():
    c = BatchCoalescer(target=100, linger_secs=60.0)
    c.add(0, _batch([1, 2]))
    other = Batch(np.array([5], dtype=np.int64),
                  {"w": np.array([9], dtype=np.int64)})
    out = c.add(0, other)
    # the incompatible batch releases the old run FIRST (order preserved)
    assert len(out) == 1 and out[0][1].columns["v"].tolist() == [1, 2]
    flushed = c.flush_all()
    assert len(flushed) == 1 and flushed[0][1].columns["w"].tolist() == [9]


def test_coalescer_sides_never_mix():
    c = BatchCoalescer(target=100, linger_secs=60.0)
    c.add(0, _batch([1]))
    c.add(1, _batch([2]))
    flushed = c.flush_all()
    assert [(s, b.columns["v"].tolist()) for s, b in flushed] == \
        [(0, [1]), (1, [2])]


def test_coalescer_linger_bound_honored_e2e(monkeypatch):
    """A rate-limited trickle (every batch far below target) must still
    flow: each fragment waits at most the linger bound."""
    monkeypatch.setenv("ARROYO_COALESCE", "1")
    monkeypatch.setenv("COALESCE_LINGER_MICROS", "5000")
    import arroyo_tpu.config as cfg

    cfg.reset_config()
    try:
        clear_sink("linger")
        prog = (
            Stream.source("impulse", {"event_rate": 2_000.0,
                                      "message_count": 400,
                                      "batch_size": 16})
            .map(lambda c: {"counter": c["counter"]}, name="ident")
            .sink("memory", {"name": "linger"})
        )
        t0 = time.perf_counter()
        LocalRunner(prog).run()
        wall = time.perf_counter() - t0
        out = Batch.concat(sink_output("linger"))
        assert len(out) == 400
        # 400 events at 2k/s is ~0.2s of stream; a broken linger (e.g.
        # waiting for the 8k-row target forever) would stall until
        # end-of-stream flush — bound the wall generously
        assert wall < 10.0
    finally:
        cfg.reset_config()


def test_coalesce_preserves_watermark_ordering(monkeypatch):
    """Windowed aggregation over many tiny batches: coalesced and
    uncoalesced runs must produce identical window contents — buffered
    records are never reordered past a watermark."""
    from arroyo_tpu.graph.logical import AggKind, AggSpec

    rng = np.random.default_rng(7)
    n = 5_000
    ts = np.sort(rng.integers(0, 3 * SEC, n)).astype(np.int64)
    src = Batch(ts, {"k": rng.integers(0, 16, n).astype(np.int64),
                     "v": rng.integers(0, 100, n).astype(np.int64)})
    # many tiny batches: memory source splits per configured batch
    batches = [src.select(np.arange(i, min(i + 64, n)))
               for i in range(0, n, 64)]

    def run_once(coalesce):
        monkeypatch.setenv("ARROYO_COALESCE", coalesce)
        clear_sink("wmord")
        prog = (Stream.source("memory", {"batches": batches})
                .watermark(max_lateness_micros=0)
                .key_by("k")
                .tumbling_aggregate(SEC // 2, [
                    AggSpec(AggKind.COUNT, None, "cnt"),
                    AggSpec(AggKind.SUM, "v", "s")])
                .sink("memory", {"name": "wmord"}))
        LocalRunner(prog).run()
        out = Batch.concat(sink_output("wmord"))
        order = np.lexsort((out.columns["window_end"],
                            np.asarray(out.key_hash, dtype=np.uint64)))
        return {c: out.columns[c][order].tolist()
                for c in ("cnt", "s", "window_end")}

    a = run_once("0")
    b = run_once("1")
    assert a == b


# -- checkpoint / restore / rescale round-trip (chained q5) ------------------


Q5_INSERT = """
CREATE TABLE nexmark WITH (
  connector = 'nexmark', event_rate = '1000000', num_events = '{n}',
  rate_limited = 'false', batch_size = '1024',
  base_time_micros = '1700000000000000'
);
CREATE TABLE sinkt (auction BIGINT, num BIGINT) WITH (
  connector = 'single_file', path = '{out}', type = 'sink');
INSERT INTO sinkt
WITH bids as (SELECT bid.auction as auction, bid.datetime as datetime
    FROM nexmark where bid is not null)
SELECT AuctionBids.auction as auction, AuctionBids.num as num
FROM (
  SELECT B1.auction, HOP(INTERVAL '2' SECOND, INTERVAL '10' SECOND)
         as window, count(*) AS num
  FROM bids B1 GROUP BY 1, 2
) AS AuctionBids
JOIN (
  SELECT max(num) AS maxn, window
  FROM (
    SELECT count(*) AS num,
           HOP(INTERVAL '2' SECOND, INTERVAL '10' SECOND) AS window
    FROM bids B2 GROUP BY B2.auction, 2
  ) AS CountBids
  GROUP BY 2
) AS MaxBids
ON AuctionBids.num = MaxBids.maxn and AuctionBids.window = MaxBids.window
"""


def _q5_rows(path):
    rows = [json.loads(line) for line in open(path)]
    return sorted((r["auction"], r["num"]) for r in rows)


@pytest.mark.slow
def test_q5_unchained_checkpoint_restores_chained_with_rescale(
        tmp_path, monkeypatch):
    """The headline round-trip: checkpoint a q5 plan UN-chained, restore
    it CHAINED at higher parallelism (overrides expanded chain-wide),
    and assert exactly-once output against an uninterrupted reference.
    Proves per-member state naming survives fusion in both directions."""
    from arroyo_tpu.sql import plan_sql

    n = 120_000
    ref_path = tmp_path / "ref.jsonl"
    out_path = tmp_path / "out.jsonl"
    url = f"file://{tmp_path}/ckpt"

    # uninterrupted chained reference
    monkeypatch.setenv("ARROYO_CHAIN", "1")
    LocalRunner(plan_sql(Q5_INSERT.format(n=n, out=ref_path),
                         parallelism=2)).run()
    reference = _q5_rows(ref_path)
    assert reference

    # phase 1: run UN-chained, checkpoint-then-stop mid-stream
    monkeypatch.setenv("ARROYO_CHAIN", "0")
    prog = plan_sql(Q5_INSERT.format(n=n, out=out_path), parallelism=2)

    async def run_phase1():
        engine = Engine.for_local(prog, "q5-rt", checkpoint_url=url)
        running = engine.start()
        await asyncio.sleep(0.35)
        await running.checkpoint(epoch=1, then_stop=True)
        assert await running.wait_for_checkpoint(1, timeout=60)
        try:
            await running.join()
        except RuntimeError:
            pass

    asyncio.run(run_phase1())

    # phase 2: rescale the aggregate CHAIN (override expanded to all
    # members) and restore CHAINED from the un-chained checkpoint
    monkeypatch.setenv("ARROYO_CHAIN", "1")
    agg_id = next(nd.operator_id for nd in prog.nodes()
                  if "aggregator" in nd.operator_id)
    overrides = expand_overrides(prog, {agg_id: 3})
    assert len(overrides) > 1, "aggregate should sit in a chain"
    prog.update_parallelism(overrides)
    chain = plan_chains(prog).group_for(agg_id)
    assert chain is not None
    assert {prog.node(m).parallelism for m in chain} == {3}

    async def run_phase2():
        engine = Engine.for_local(prog, "q5-rt", checkpoint_url=url,
                                  restore_epoch=1)
        running = engine.start()
        await running.join()

    asyncio.run(run_phase2())
    assert _q5_rows(out_path) == reference


# -- ingest-spine fusion / shuffle-1 chaining / update coalescing (PR 9) -----


def test_ingest_spine_zero_dispatches_same_rows(monkeypatch):
    """The host spine runs elementwise chains with no kernel dispatch at
    all, emitting exactly the rows the jitted per-member path emits."""
    from arroyo_tpu.obs import perf

    monkeypatch.setenv("ARROYO_CHAIN", "1")
    monkeypatch.setenv("ARROYO_COALESCE", "0")

    def run(fuse):
        monkeypatch.setenv("ARROYO_CHAIN_FUSE_INGEST", fuse)
        sink = f"spine-{fuse}"
        clear_sink(sink)
        before = perf.counter("kernel_dispatches")
        _run_engine(_map_filter_prog(sink, n=6000), f"spine-job-{fuse}")
        d = perf.counter("kernel_dispatches") - before
        return d, Batch.concat(sink_output(sink))

    d_jit, rows_jit = run("0")
    d_spine, rows_spine = run("1")
    assert d_spine == 0, d_spine
    assert d_jit > 0
    np.testing.assert_array_equal(
        np.sort(rows_spine.columns["tripled"]),
        np.sort(rows_jit.columns["tripled"]))
    assert sorted(rows_spine.columns["counter"].tolist()) == \
        sorted(rows_jit.columns["counter"].tolist())


def test_spine_member_counts_survive_filters(monkeypatch):
    """Per-member recv/sent rollups stay exact through a spine whose
    predicate drops rows — the autoscaler's per-operator signals must
    not blur when members fuse."""
    from arroyo_tpu.obs.metrics import job_operator_summary

    monkeypatch.setenv("ARROYO_CHAIN", "1")
    monkeypatch.setenv("ARROYO_CHAIN_FUSE_INGEST", "1")
    clear_sink("spine-counts")
    prog = _map_filter_prog("spine-counts", n=4000)
    engine = _run_engine(prog, "spine-counts-job")
    chained = next(h for h in engine.subtasks.values()
                   if len(h.member_ids) > 1)
    assert len(chained.member_ids) == 3
    summary = job_operator_summary("spine-counts-job")
    double, triple, evens = chained.member_ids
    # maps are 1:1; the filter keeps counter % 2 == 0 (tripled = 3c)
    assert summary[double].get("messages_sent_total") == 4000
    assert summary[triple].get("messages_recv_total") == 4000
    assert summary[triple].get("messages_sent_total") == 4000
    assert summary[evens].get("messages_recv_total") == 4000
    assert summary[evens].get("messages_sent_total") == 2000


def test_shuffle1_chains_through_keyed_window(monkeypatch):
    """A parallelism-1 keyed window pipeline fuses into one task across
    the (routing-trivial) shuffle edge, with identical output rows."""
    rng = np.random.default_rng(7)
    ts = np.sort(rng.integers(0, 4 * SEC, 4000)).astype(np.int64)
    batches = [Batch(ts[i:i + 256],
                     {"k": rng.integers(0, 9, len(ts[i:i + 256])),
                      "v": np.ones(len(ts[i:i + 256]), dtype=np.int64)})
               for i in range(0, len(ts), 256)]

    from arroyo_tpu import AggSpec, TumblingWindow

    def build(sink):
        return (Stream.source("memory", {"batches": batches})
                .watermark(max_lateness_micros=0)
                .key_by("k")
                .window(TumblingWindow(SEC),
                        [AggSpec(AggKind.COUNT, None, "n")])
                .sink("memory", {"name": sink}))

    def run(flag):
        monkeypatch.setenv("ARROYO_CHAIN_SHUFFLE1", flag)
        sink = f"sh1-{flag}"
        clear_sink(sink)
        engine = _run_engine(build(sink), f"sh1-job-{flag}")
        rows = Batch.concat(sink_output(sink))
        key = sorted(zip(rows.columns["k"].tolist(),
                         rows.columns["window_end"].tolist(),
                         rows.columns["n"].tolist()))
        return len(engine.subtasks), key

    n_off, rows_off = run("0")
    n_on, rows_on = run("1")
    assert rows_on == rows_off
    assert n_on < n_off, (n_on, n_off)


def test_shuffle_chains_only_at_parallelism_1():
    """A plain SHUFFLE edge joins a chain iff both ends run at
    parallelism 1 (identity routing); at any other parallelism it
    breaks the chain exactly as before."""
    def build():
        return (
            Stream.source("impulse", {"event_rate": 0.0,
                                      "message_count": 10})
            .map(lambda c: {"counter": c["counter"],
                            "b": c["counter"] % 3}, name="m1")
            .key_by("b")
            .count()
            .sink("memory", {"name": "sh2"})
        )

    prog = build()
    plan = plan_chains(prog)
    count_id = next(n.operator_id for n in prog.nodes()
                    if n.operator_id.endswith("_count"))
    grp = plan.group_for(count_id)
    assert grp is not None, "p1 shuffle should chain into the count"
    # now the same shape at parallelism 2: the shuffle breaks the chain
    prog2 = build()
    for n in prog2.nodes():
        if n.operator.kind.value != "connector_sink":
            n.parallelism = 2
    plan2 = plan_chains(prog2)
    validate_chain_plan(prog2, plan2)
    for g in plan2.groups:
        for u, v in zip(g, g[1:]):
            assert prog2.edge(u, v).typ.value == "forward"


def test_update_coalescing_parity_with_snapshot_roundtrip(monkeypatch):
    """Deferred window-state scatters are invisible to emission and
    checkpointing: same fired panes as the immediate-dispatch path, and
    a snapshot taken mid-buffer flushes first (a restore of it resumes
    bit-identically)."""
    from arroyo_tpu.ops.keyed_bins import KeyedBinState

    rng = np.random.default_rng(3)
    aggs = (AggSpec(AggKind.COUNT, None, "n"), AggSpec(AggKind.SUM, "v", "s"))

    def feed(state, upto):
        for i in range(upto):
            kh = rng2.integers(0, 50, 300).astype(np.uint64)
            t = rng2.integers(i * SEC, (i + 1) * SEC, 300).astype(np.int64)
            v = rng2.integers(1, 9, 300).astype(np.float64)
            state.update(kh, t, {"v": v})

    def fire(state):
        out = state.fire_panes(10 * SEC)
        if out is None:
            return None
        keys, cols, wend, cnts = out
        return sorted(zip(keys.tolist(), wend.tolist(),
                          cols["n"].tolist(), cols["s"].tolist()))

    results = {}
    for flag in ("0", "1"):
        monkeypatch.setenv("ARROYO_UPDATE_COALESCE", flag)
        rng2 = np.random.default_rng(11)
        st = KeyedBinState(aggs, SEC, 2 * SEC, capacity=64)
        feed(st, 6)
        snap = {k: np.copy(v) for k, v in st.snapshot().items()}
        # restore the mid-stream snapshot into a fresh state and finish
        st2 = KeyedBinState(aggs, SEC, 2 * SEC, capacity=64)
        st2.restore(snap)
        feed(st2, 2)
        results[flag] = fire(st2)
    assert results["1"] == results["0"]
    assert results["1"] is not None
