"""Factor-window sharing (graph/factor_windows.py): cost-model
decisions (incl. the don't-factor cases), the ARROYO_FACTOR_WINDOWS=0
bit-for-bit escape, sanitized row parity factored x mesh on/off, and
the factored <-> unfactored checkpoint interchange with a mid-restore
rescale."""

import asyncio
import json
import os

import pytest

from arroyo_tpu import Stream
from arroyo_tpu.engine.engine import Engine, LocalRunner
from arroyo_tpu.graph.factor_windows import (
    apply_factor_windows,
    expand_overrides,
    factor_groups,
    plan_factor_windows,
)
from arroyo_tpu.graph.logical import AggKind, AggSpec, OpKind
from arroyo_tpu.sql import plan_sql

SECOND = 1_000_000

TWO_WINDOW_SQL = """
CREATE TABLE nexmark WITH (
  connector = 'nexmark', event_rate = '1000000', num_events = '{n}',
  rate_limited = 'false', batch_size = '2048',
  base_time_micros = '1700000000000000'
);
CREATE TABLE s1 (auction BIGINT, window_end BIGINT, num BIGINT) WITH (
  connector = 'memory', name = 'fw1', type = 'sink');
CREATE TABLE s2 (auction BIGINT, window_end BIGINT, tot BIGINT) WITH (
  connector = 'memory', name = 'fw2', type = 'sink');
INSERT INTO s1
SELECT bid.auction as auction,
       HOP(INTERVAL '2' SECOND, INTERVAL '10' SECOND) as window,
       count(*) AS num
FROM nexmark WHERE bid is not null GROUP BY 1, 2;
INSERT INTO s2
SELECT bid.auction as auction,
       HOP(INTERVAL '2' SECOND, INTERVAL '4' SECOND) as window,
       sum(bid.price) AS tot
FROM nexmark WHERE bid is not null GROUP BY 1, 2;
"""


def _kinds(prog):
    return sorted(n.operator.kind.value for n in prog.nodes())


def _stream_pair(width_a, slide_a, width_b, slide_b, aggs_b=None,
                 key_b=None):
    """Two Stream-API window aggregates off one shared keyed source."""
    src = Stream.source("impulse", {"message_count": 100}) \
        .watermark(name="wm")
    keyed = src.key_by("counter")
    keyed.sliding_aggregate(width_a, slide_a,
                            [AggSpec(AggKind.COUNT, None, "c")],
                            name="agg_a").sink("blackhole", {})
    second = keyed if key_b is None else src.key_by(key_b)
    second.sliding_aggregate(
        width_b, slide_b,
        aggs_b or [AggSpec(AggKind.SUM, "counter", "s")],
        name="agg_b").sink("blackhole", {})
    return keyed.program


# -- pass unit tests ---------------------------------------------------------


def test_sql_plan_factors(monkeypatch):
    monkeypatch.setenv("ARROYO_FACTOR_WINDOWS", "auto")
    prog = plan_sql(TWO_WINDOW_SQL.format(n=1000))
    kinds = _kinds(prog)
    assert kinds.count("window_factor") == 1
    assert kinds.count("derived_window") == 2
    assert kinds.count("sliding_window_aggregator") == 0
    # one shared keying chain: the two private agg_input/key_by tails
    # are gone
    assert kinds.count("key_by") == 1
    decisions = prog.factor_decisions
    shared = [d for d in decisions if d.shared]
    assert len(shared) == 1
    d = shared[0]
    assert d.pane_micros == 2 * SECOND  # gcd(10s, 2s, 4s, 2s)
    assert d.inputs["k"] == 2 and d.factor_node is not None
    # the factor's SHUFFLE feed is keyed like the members were
    fid = d.factor_node
    (src, _, data), = prog.graph.in_edges(fid, data=True)
    assert data["edge"].key_schema == "auction"


def test_knob_off_reproduces_topology(monkeypatch):
    """ARROYO_FACTOR_WINDOWS=0 pins today's (unfactored) topology
    bit-for-bit: the plan hash with the knob off matches a second
    knob-off plan, contains the original aggregator kinds, and the
    engine-side re-application is a no-op."""
    monkeypatch.setenv("ARROYO_FACTOR_WINDOWS", "0")
    prog = plan_sql(TWO_WINDOW_SQL.format(n=1000))
    again = plan_sql(TWO_WINDOW_SQL.format(n=1000))
    assert prog.get_hash() == again.get_hash()
    kinds = _kinds(prog)
    assert kinds.count("sliding_window_aggregator") == 2
    assert "window_factor" not in kinds
    assert apply_factor_windows(prog) == []
    assert prog.get_hash() == again.get_hash()


def test_stream_api_direct_shape_factors(monkeypatch):
    monkeypatch.setenv("ARROYO_FACTOR_WINDOWS", "auto")
    prog = _stream_pair(10 * SECOND, 2 * SECOND, 4 * SECOND, 2 * SECOND)
    decisions = apply_factor_windows(prog)
    assert [d.shared for d in decisions] == [True]
    groups = factor_groups(prog)
    assert len(groups) == 1
    (fid, derived), = groups.items()
    assert len(derived) == 2
    # validator accepts the factored shape
    from arroyo_tpu.analysis.plan_validator import check_program

    check_program(prog)


def test_no_factor_single_member(monkeypatch):
    monkeypatch.setenv("ARROYO_FACTOR_WINDOWS", "auto")
    src = Stream.source("impulse", {"message_count": 10}).watermark()
    src.key_by("counter").sliding_aggregate(
        4 * SECOND, 2 * SECOND,
        [AggSpec(AggKind.COUNT, None, "c")]).sink("blackhole", {})
    prog = src.program
    assert plan_factor_windows(prog) == []
    assert apply_factor_windows(prog) == []


def test_no_factor_non_decomposable(monkeypatch):
    """A UDAF member is not bin-mergeable: the group never forms."""
    monkeypatch.setenv("ARROYO_FACTOR_WINDOWS", "auto")
    prog = _stream_pair(
        10 * SECOND, 2 * SECOND, 4 * SECOND, 2 * SECOND,
        aggs_b=[AggSpec(AggKind.UDAF, "counter", "u",
                        fn=lambda v: float(v.sum()))])
    assert [d for d in plan_factor_windows(prog) if d.shared] == []
    assert "window_factor" not in _kinds(prog)


def test_no_factor_mismatched_keys(monkeypatch):
    """Members keyed by different columns never share pane state."""
    monkeypatch.setenv("ARROYO_FACTOR_WINDOWS", "auto")
    prog = _stream_pair(10 * SECOND, 2 * SECOND, 4 * SECOND, 2 * SECOND,
                        key_b="subtask_index")
    apply_factor_windows(prog)
    assert "window_factor" not in _kinds(prog)


def test_no_factor_pathological_gcd(monkeypatch):
    """Near-coprime slides gcd to a micro-pane: the cost model refuses
    (the factor ring would fire min(slide)/gcd times more often)."""
    monkeypatch.setenv("ARROYO_FACTOR_WINDOWS", "auto")
    prog = _stream_pair(2 * SECOND + 2, 2 * SECOND + 2,
                        4 * SECOND, 2 * SECOND)
    decisions = plan_factor_windows(prog)
    assert len(decisions) == 1
    d = decisions[0]
    assert not d.shared and d.reason == "pane_ratio_exceeded"
    assert d.pane_micros == 2  # gcd(2000002, 4000000, 2000000)
    apply_factor_windows(prog)
    assert "window_factor" not in _kinds(prog)


def test_expand_overrides_covers_group(monkeypatch):
    monkeypatch.setenv("ARROYO_FACTOR_WINDOWS", "auto")
    prog = _stream_pair(10 * SECOND, 2 * SECOND, 4 * SECOND, 2 * SECOND)
    apply_factor_windows(prog)
    (fid, derived), = factor_groups(prog).items()
    out = expand_overrides(prog, {derived[0]: 3})
    assert out[fid] == 3 and all(out[m] == 3 for m in derived)


# -- sanitized row-parity matrix: factored x mesh on/off ---------------------


def _run_two_window(monkeypatch, factor: str, mesh: str):
    from arroyo_tpu.connectors.memory import clear_sink, sink_output

    monkeypatch.setenv("ARROYO_FACTOR_WINDOWS", factor)
    monkeypatch.setenv("ARROYO_MESH", mesh)
    monkeypatch.setenv("ARROYO_SANITIZE", "1")
    prog = plan_sql(TWO_WINDOW_SQL.format(n=30000))
    clear_sink("fw1")
    clear_sink("fw2")
    runner = LocalRunner(prog)
    runner.run()
    san = runner.engine.sanitizer
    assert san is not None and not san.violations, san and san.violations
    out = []
    for name, cols in (("fw1", ("auction", "window_end", "num")),
                       ("fw2", ("auction", "window_end", "tot"))):
        out.append(sorted(
            tuple(int(b.columns[c][i]) for c in cols)
            for b in sink_output(name) for i in range(len(b))))
    return out


def test_row_parity_factored_x_mesh(monkeypatch):
    ref = _run_two_window(monkeypatch, "0", "off")
    assert all(len(r) for r in ref)
    for factor in ("auto",):
        for mesh in ("off", "auto"):
            got = _run_two_window(monkeypatch, factor, mesh)
            assert got == ref, (factor, mesh, len(got[0]), len(ref[0]))
    # unfactored mesh run closes the matrix
    assert _run_two_window(monkeypatch, "0", "auto") == ref


# -- checkpoint interchange with mid-restore rescale -------------------------

RT_SQL = """
CREATE TABLE nexmark WITH (
  connector = 'nexmark', event_rate = '60000', num_events = '60000',
  rate_limited = 'true', batch_size = '2048',
  base_time_micros = '1700000000000000'
);
CREATE TABLE s1 (auction BIGINT, window_end BIGINT, num BIGINT) WITH (
  connector = 'single_file', path = '{o1}', type = 'sink');
CREATE TABLE s2 (auction BIGINT, window_end BIGINT, tot BIGINT) WITH (
  connector = 'single_file', path = '{o2}', type = 'sink');
INSERT INTO s1
SELECT bid.auction as auction,
       HOP(INTERVAL '2' SECOND, INTERVAL '10' SECOND) as window,
       count(*) AS num
FROM nexmark WHERE bid is not null GROUP BY 1, 2;
INSERT INTO s2
SELECT bid.auction as auction,
       HOP(INTERVAL '2' SECOND, INTERVAL '4' SECOND) as window,
       sum(bid.price) AS tot
FROM nexmark WHERE bid is not null GROUP BY 1, 2;
"""


def _rows_of(path):
    with open(path) as f:
        return sorted(tuple(sorted(json.loads(line).items()))
                      for line in f)


@pytest.mark.slow
def test_checkpoint_interchange_with_rescale(tmp_path, monkeypatch):
    """factored -> unfactored -> factored epoch interchange, with a
    2 -> 3 rescale applied at the final (factored) restore.  The factor
    drains its pending panes at every barrier, so no epoch ever strands
    mass in a table the other topology cannot restore; exactly-once
    output is pinned against an uninterrupted factored reference."""
    monkeypatch.setenv("ARROYO_SANITIZE", "1")
    monkeypatch.setenv("ARROYO_FACTOR_WINDOWS", "auto")
    url = f"file://{tmp_path}/ckpt"
    r1, r2 = str(tmp_path / "ref1.jsonl"), str(tmp_path / "ref2.jsonl")
    LocalRunner(plan_sql(RT_SQL.format(o1=r1, o2=r2),
                         parallelism=2)).run()
    ref = (_rows_of(r1), _rows_of(r2))
    assert ref[0] and ref[1]

    o1, o2 = str(tmp_path / "out1.jsonl"), str(tmp_path / "out2.jsonl")

    def make_prog(factor: str, rescale_to=None):
        monkeypatch.setenv("ARROYO_FACTOR_WINDOWS", factor)
        prog = plan_sql(RT_SQL.format(o1=o1, o2=o2), parallelism=2)
        factored = any(n.operator.kind is OpKind.WINDOW_FACTOR
                       for n in prog.nodes())
        assert factored == (factor == "auto")
        if rescale_to is not None:
            from arroyo_tpu.graph.chaining import (
                expand_overrides as chain_expand,
            )

            member = next(n.operator_id for n in prog.nodes()
                          if n.operator.kind is OpKind.DERIVED_WINDOW)
            # same fixpoint as controller.rescale_job: factor expansion
            # adds members whose chains then need the override too
            overrides, prev = {member: rescale_to}, None
            while overrides != prev:
                prev = overrides
                overrides = chain_expand(prog, overrides)
                overrides = expand_overrides(prog, overrides)
            prog.update_parallelism(overrides)
        return prog

    async def phase(prog, restore, epoch):
        engine = Engine.for_local(prog, "factor-rt", checkpoint_url=url,
                                  restore_epoch=restore)
        running = engine.start()
        if epoch is not None:
            await asyncio.sleep(0.35)
            await running.checkpoint(epoch=epoch, then_stop=True)
            assert await running.wait_for_checkpoint(epoch, timeout=60)
            try:
                await running.join()
            except RuntimeError:
                pass
        else:
            await running.join()
        san = engine.sanitizer
        assert san is None or not san.violations

    asyncio.run(phase(make_prog("auto"), None, 1))
    asyncio.run(phase(make_prog("0"), 1, 2))
    asyncio.run(phase(make_prog("auto", rescale_to=3), 2, None))

    assert (_rows_of(o1), _rows_of(o2)) == ref
