"""Core type tests, mirroring the reference's unit tests for key-range
partitioning (arroyo-types/src/lib.rs:838-874)."""

import numpy as np
import pytest

from arroyo_tpu.types import (
    Batch,
    U64_MAX,
    hash_columns,
    hash_u64,
    range_for_server,
    server_for_hash,
    server_for_hash_array,
)


def test_range_for_server_adjacent():
    # ranges must tile the u64 space exactly (lib.rs:843-858)
    n = 6
    for i in range(n - 1):
        r1 = range_for_server(i, n)
        r2 = range_for_server(i + 1, n)
        assert r1[1] + 1 == r2[0], "ranges not adjacent"
    assert range_for_server(n - 1, n)[1] == int(U64_MAX)


def test_server_for_hash_max():
    # u64::MAX maps into the owning range (lib.rs:860-874)
    n = 2
    idx = server_for_hash(int(U64_MAX), n)
    lo, hi = range_for_server(idx, n)
    assert lo <= int(U64_MAX) <= hi


@pytest.mark.parametrize("n", [1, 2, 3, 7, 16])
def test_server_for_hash_consistent_with_ranges(n):
    rng = np.random.default_rng(0)
    xs = rng.integers(0, 1 << 63, size=200, dtype=np.uint64) * 2 + rng.integers(0, 2, 200).astype(np.uint64)
    for x in xs.tolist():
        i = server_for_hash(x, n)
        lo, hi = range_for_server(i, n)
        assert lo <= x <= hi
    # vectorized matches scalar
    vec = server_for_hash_array(xs, n)
    assert all(vec[i] == server_for_hash(xs[i], n) for i in range(len(xs)))


def test_hash_spreads_uniformly():
    keys = np.arange(10_000, dtype=np.int64)
    h = hash_u64(keys)
    shards = server_for_hash_array(h, 8)
    counts = np.bincount(shards, minlength=8)
    assert counts.min() > 1000  # ~1250 expected per shard


def test_hash_columns_strings_stable():
    a = np.array(["x", "y", "x"], dtype=object)
    h1 = hash_columns([a])
    h2 = hash_columns([a])
    np.testing.assert_array_equal(h1, h2)
    assert h1[0] == h1[2] and h1[0] != h1[1]


def test_batch_select_concat_roundtrip():
    b = Batch(np.array([10, 20, 30]), {"v": np.array([1.0, 2.0, 3.0])})
    b = b.with_key(["v"])
    sel = b.select(np.array([0, 2]))
    assert len(sel) == 2 and sel.key_hash is not None
    cat = Batch.concat([sel, sel])
    assert len(cat) == 4


def test_batch_arrow_roundtrip():
    b = Batch(np.array([10, 20]), {
        "v": np.array([1.5, 2.5]),
        "s": np.array(["a", "b"], dtype=object),
    })
    t = b.to_arrow()
    back = Batch.from_arrow(t)
    np.testing.assert_array_equal(back.timestamp, b.timestamp)
    np.testing.assert_array_equal(back.columns["v"], b.columns["v"])
    assert list(back.columns["s"]) == ["a", "b"]
