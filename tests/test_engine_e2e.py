"""End-to-end engine slice: impulse -> jitted map/filter -> sink, the
"minimum end-to-end slice" of SURVEY.md §7 step 3; plus watermark/window
plumbing, multi-subtask shuffles, and checkpoint barrier flow."""

import asyncio
import json

import jax.numpy as jnp
import numpy as np
import pytest

from arroyo_tpu import Batch, Program, Stream
from arroyo_tpu.connectors.memory import clear_sink, sink_output
from arroyo_tpu.engine.engine import Engine, LocalRunner
from arroyo_tpu.types import StopMode


def collect_rows(name):
    batches = sink_output(name)
    if not batches:
        return {}
    merged = Batch.concat(batches)
    return merged


def test_impulse_map_filter_memory():
    clear_sink("t1")
    prog = (
        Stream.source("impulse", {"event_rate": 0.0, "message_count": 1000,
                                  "batch_size": 128})
        .map(lambda c: {"counter": c["counter"],
                        "doubled": c["counter"] * 2}, name="double")
        .filter(lambda c: c["doubled"] % 4 == 0, name="quarters")
        .sink("memory", {"name": "t1"})
    )
    LocalRunner(prog).run()
    out = collect_rows("t1")
    assert len(out) == 500
    assert np.all(out.columns["doubled"] % 4 == 0)
    assert set(out.columns["counter"].tolist()) == set(range(0, 1000, 2))


def test_impulse_parallel_shuffle_count():
    clear_sink("t2")
    prog = (
        Stream.source("impulse", {"event_rate": 0.0, "message_count": 400,
                                  "batch_size": 64}, parallelism=2)
        .map(lambda c: {"counter": c["counter"],
                        "bucket": c["counter"] % 10}, name="bucket")
        .key_by("bucket")
        .count()
        .sink("memory", {"name": "t2"}, parallelism=1)
    )
    LocalRunner(prog).run()
    out = collect_rows("t2")
    assert len(out) > 0
    # final count per bucket must be 40 (last update per key wins)
    finals = {}
    for kh, c in zip(out.key_hash.tolist(), out.columns["count"].tolist()):
        finals[kh] = max(finals.get(kh, 0), c)
    assert len(finals) == 10
    assert all(v == 40 for v in finals.values())


def test_single_file_roundtrip(tmp_path):
    src = tmp_path / "in.jsonl"
    dst = tmp_path / "out.jsonl"
    with open(src, "w") as f:
        for i in range(50):
            f.write(json.dumps({"x": i}) + "\n")
    prog = (
        Stream.source("single_file", {"path": str(src)})
        .map(lambda c: {"x": c["x"], "y": c["x"] + 1}, name="inc")
        .sink("single_file", {"path": str(dst)})
    )
    LocalRunner(prog).run()
    rows = [json.loads(l) for l in open(dst)]
    assert len(rows) == 50
    assert all(r["y"] == r["x"] + 1 for r in rows)


def test_checkpoint_barrier_flow():
    """Inject a barrier mid-stream; every operator must checkpoint and the
    responses must include completed events for all subtasks."""
    clear_sink("t3")

    async def scenario():
        prog = (
            Stream.source("impulse", {"event_rate": 5_000.0,
                                      "message_count": 2000,
                                      "batch_size": 100})
            .map(lambda c: {"counter": c["counter"]}, name="ident")
            .sink("memory", {"name": "t3"})
        )
        engine = Engine.for_local(prog, "ckpt-job")
        running = engine.start()
        await asyncio.sleep(0.05)
        await running.checkpoint(epoch=1)
        return await running.join()

    resps = asyncio.run(scenario())
    completed = [r for r in resps if r.kind == "checkpoint_completed"]
    # 3 operators x 1 subtask
    assert len(completed) == 3
    assert all(r.subtask_metadata.epoch == 1 for r in completed)
    out = collect_rows("t3")
    assert len(out) == 2000


def test_graceful_stop():
    clear_sink("t4")

    async def scenario():
        prog = (
            Stream.source("impulse", {"event_rate": 10_000.0, "batch_size": 50})
            .sink("memory", {"name": "t4"})
        )
        engine = Engine.for_local(prog, "stop-job")
        running = engine.start()
        await asyncio.sleep(0.1)
        await running.stop(StopMode.GRACEFUL)
        return await running.join()

    resps = asyncio.run(scenario())
    finished = [r for r in resps if r.kind == "task_finished"]
    assert len(finished) == 2
    assert len(collect_rows("t4")) > 0


def test_watermarks_propagate():
    clear_sink("t5")
    prog = (
        Stream.source("impulse", {"event_rate": 0.0, "message_count": 100,
                                  "event_time_interval_micros": 1000,
                                  "batch_size": 10})
        .watermark(max_lateness_micros=0)
        .sink("memory", {"name": "t5"})
    )
    LocalRunner(prog).run()
    assert len(collect_rows("t5")) == 100


def test_pipeline_determinism_across_runs():
    """SURVEY §5: in place of the reference's (absent) race detection, the
    build leans on determinism — the same pipeline over the same input
    must produce bit-identical float aggregates run after run."""
    from arroyo_tpu.graph.logical import AggKind, AggSpec

    rng = np.random.default_rng(3)
    n = 20_000
    ts = np.sort(rng.integers(0, 3_000_000, n)).astype(np.int64)
    src = Batch(ts, {"k": rng.integers(0, 64, n).astype(np.int64),
                     "v": rng.random(n)})

    def run_once():
        clear_sink("det")
        prog = (Stream.source("memory", {"batches": [src]})
                .watermark(max_lateness_micros=0)
                .key_by("k")
                .sliding_aggregate(1_000_000, 250_000, [
                    AggSpec(AggKind.SUM, "v", "s"),
                    AggSpec(AggKind.AVG, "v", "a"),
                    AggSpec(AggKind.MIN, "v", "lo"),
                    AggSpec(AggKind.MAX, "v", "hi"),
                ])
                .sink("memory", {"name": "det"}))
        LocalRunner(prog).run()
        out = Batch.concat(sink_output("det"))
        order = np.lexsort((out.columns["window_end"],
                            np.asarray(out.key_hash, dtype=np.uint64)))
        return {c: out.columns[c][order] for c in ("s", "a", "lo", "hi")}

    a, b = run_once(), run_once()
    for c in a:
        np.testing.assert_array_equal(a[c], b[c])  # BIT-identical
