"""Test configuration: force JAX onto a virtual 8-device CPU mesh so sharding
tests run without TPU hardware (the driver dry-runs the multi-chip path the
same way)."""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import asyncio  # noqa: E402

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def run_async():
    def _run(coro):
        return asyncio.run(coro)

    return _run


@pytest.fixture
def rng():
    return np.random.default_rng(42)
