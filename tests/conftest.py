"""Test configuration: force JAX onto a virtual 8-device CPU mesh so sharding
tests run without TPU hardware (the driver dry-runs the multi-chip path the
same way).

NOTE: if the axon TPU tunnel is flaky, run tests with the axon plugin
disabled entirely (its sitecustomize registration is env-gated):

    env -u PALLAS_AXON_POOL_IPS python -m pytest tests/ -q
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"  # tests always run on the CPU mesh

# arroyosan runtime sanitizer: tier-1 runs with the streaming-invariant
# assertions armed (watermark monotonicity, barrier alignment, coalescer
# flush-before-control, snapshot/upload atomicity, checkpoint
# completeness) — a violation fails the offending test with the event
# ring instead of passing on corrupted output.  setdefault so a test or
# dev run can still opt out with ARROYO_SANITIZE=0.
os.environ.setdefault("ARROYO_SANITIZE", "1")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

if "jax" in sys.modules:
    # The axon sitecustomize plugin imports jax at interpreter start, before
    # this conftest runs — env vars alone are then too late. The backend
    # itself is created lazily, so flipping the config here still wins.
    import jax

    jax.config.update("jax_platforms", "cpu")
    assert jax.default_backend() == "cpu" and len(jax.devices()) == 8, (
        "axon plugin initialized a JAX backend before conftest could force "
        "the 8-device CPU mesh; run with `env -u PALLAS_AXON_POOL_IPS`")

import asyncio  # noqa: E402

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def run_async():
    def _run(coro):
        return asyncio.run(coro)

    return _run


@pytest.fixture
def rng():
    return np.random.default_rng(42)
