"""Distributed control/data plane tests: a real controller + workers over
loopback gRPC and the TCP data plane — the analog of the reference's integ
suite (integ/src/main.rs) plus worker-level network tests
(network_manager.rs:310-427)."""

import asyncio
import json

import numpy as np
import pytest

from arroyo_tpu import AggKind, AggSpec, Stream
from arroyo_tpu.controller.controller import ControllerServer
from arroyo_tpu.controller.scheduler import InProcessScheduler
from arroyo_tpu.controller.state_machine import JobState, StateMachine
from arroyo_tpu.network.data_plane import (
    NetworkManager,
    decode_message,
    encode_message,
)
from arroyo_tpu.types import Batch, Message, Watermark


def test_state_machine_transitions():
    sm = StateMachine("j1")
    sm.transition(JobState.COMPILING)
    sm.transition(JobState.SCHEDULING)
    sm.transition(JobState.RUNNING)
    with pytest.raises(ValueError):
        sm.transition(JobState.SCHEDULING)  # invalid from RUNNING
    assert sm.try_recover("boom")
    assert sm.state == JobState.RECOVERING
    sm.transition(JobState.SCHEDULING)
    sm.transition(JobState.RUNNING)
    # exceed restart budget
    for _ in range(20):
        if not sm.try_recover("again"):
            break
        sm.transition(JobState.SCHEDULING)
        sm.transition(JobState.RUNNING)
    assert sm.state == JobState.FAILED


def test_message_codec_roundtrip():
    b = Batch(np.array([1, 2], dtype=np.int64),
              {"x": np.array([10, 20], dtype=np.int64),
               "s": np.array(["a", "b"], dtype=object)}).with_key(["x"])
    for msg in [Message.record(b), Message.wm(Watermark.event_time(42)),
                Message.wm(Watermark.idle()), Message.stop(),
                Message.end_of_data()]:
        kind, payload = encode_message(msg)
        out = decode_message(kind, payload)
        assert out.kind == msg.kind
        if msg.batch is not None:
            np.testing.assert_array_equal(out.batch.timestamp, b.timestamp)
            np.testing.assert_array_equal(out.batch.key_hash, b.key_hash)
            assert list(out.batch.columns["s"]) == ["a", "b"]


def test_network_loopback(run_async):
    """Frame a batch through a real socket (network_manager.rs:310-427)."""

    async def scenario():
        nm_in = NetworkManager()
        q: asyncio.Queue = asyncio.Queue()
        quad = ("op1", 0, "op2", 1)
        nm_in.register_in_edge(quad, q)
        port = await nm_in.open_listener("127.0.0.1")

        nm_out = NetworkManager()
        await nm_out.connect(f"127.0.0.1:{port}")
        send = nm_out.remote_sender(f"127.0.0.1:{port}", quad)

        b = Batch(np.arange(100, dtype=np.int64),
                  {"v": np.arange(100, dtype=np.int64)})
        await send(Message.record(b))
        await send(Message.wm(Watermark.event_time(7)))
        m1 = await asyncio.wait_for(q.get(), 5)
        m2 = await asyncio.wait_for(q.get(), 5)
        await nm_out.close()
        await nm_in.close()
        return m1, m2

    m1, m2 = run_async(scenario())
    assert len(m1.batch) == 100
    assert m2.watermark.time == 7


def test_network_schema_written_once_per_edge(run_async):
    """Encode fast path: after the first full frame per edge, record
    frames are schema-less continuations decoded against the receiver's
    cached schema; a schema change mid-stream re-sends a full frame."""

    async def scenario():
        nm_in = NetworkManager()
        q: asyncio.Queue = asyncio.Queue()
        quad = ("opA", 0, "opB", 0)
        nm_in.register_in_edge(quad, q)
        port = await nm_in.open_listener("127.0.0.1")

        nm_out = NetworkManager()
        await nm_out.connect(f"127.0.0.1:{port}")
        send = nm_out.remote_sender(f"127.0.0.1:{port}", quad)

        def mk(vals, keyed=True):
            b = Batch(np.arange(len(vals), dtype=np.int64),
                      {"v": np.asarray(vals, dtype=np.int64)})
            return b.with_key(["v"]) if keyed else b

        batches = [mk([1, 2, 3]), mk([4, 5]), mk([6])]
        for b in batches:
            await send(Message.record(b))
        # schema change (no key hash column): full frame again, then a
        # continuation under the NEW schema
        changed = [mk([7, 8], keyed=False), mk([9], keyed=False)]
        for b in changed:
            await send(Message.record(b))
        got = [await asyncio.wait_for(q.get(), 5) for _ in range(5)]
        schema_cached = quad in nm_in._edge_schemas
        await nm_out.close()
        await nm_in.close()
        return got, schema_cached

    got, schema_cached = run_async(scenario())
    assert schema_cached
    assert [m.batch.columns["v"].tolist() for m in got] == [
        [1, 2, 3], [4, 5], [6], [7, 8], [9]]
    # key metadata survives the continuation path
    assert got[1].batch.key_cols == ("v",)
    assert got[1].batch.key_hash is not None
    assert got[3].batch.key_hash is None and got[3].batch.key_cols == ()


@pytest.mark.parametrize("n_workers", [1, 2])
def test_cluster_pipeline(tmp_path, n_workers):
    """Submit a pipeline to a real controller; workers execute it across
    processes-worth of isolation (in-process scheduler, real gRPC + TCP),
    including a cross-worker shuffle; verify output and FINISHED state."""
    out_path = tmp_path / "out.jsonl"

    async def scenario():
        ctrl = ControllerServer(InProcessScheduler())
        await ctrl.start()
        prog = (
            Stream.source("impulse", {"event_rate": 0.0, "message_count": 2000,
                                      "event_time_interval_micros": 1000,
                                      "batch_size": 100}, parallelism=2)
            .watermark(max_lateness_micros=0)
            .map(lambda c: {"counter": c["counter"],
                            "bucket": c["counter"] % 5}, name="b")
            .key_by("bucket")
            .tumbling_aggregate(
                200 * 1000, [AggSpec(AggKind.COUNT, None, "cnt")],
                parallelism=2)
            .sink("single_file", {"path": str(out_path)}, parallelism=1)
        )
        job_id = await ctrl.submit_job(
            prog, checkpoint_url=f"file://{tmp_path}/ckpt",
            n_workers=n_workers)
        state = await ctrl.wait_for_state(job_id, JobState.FINISHED,
                                          timeout=60)
        await ctrl.scheduler.stop_workers(job_id)
        await ctrl.stop()
        return state

    state = asyncio.run(scenario())
    assert state == JobState.FINISHED
    rows = [json.loads(l) for l in open(out_path)]
    assert sum(r["cnt"] for r in rows) == 2000


@pytest.mark.slow
def test_cluster_checkpoint_and_stop(tmp_path):
    """Periodic checkpoints complete at the job level; graceful stop with
    checkpoint reaches STOPPED; restart restores and finishes the stream."""
    out_path = tmp_path / "out.jsonl"
    ckpt_url = f"file://{tmp_path}/ckpt"

    def build():
        # 3s of rate-limited runway: the stop-with-checkpoint below must
        # land while the stream is still flowing, and warm compile
        # caches make the pipeline reach full rate sooner
        return (
            Stream.source("impulse", {"event_rate": 20_000.0,
                                      "message_count": 60_000,
                                      "event_time_interval_micros": 1000,
                                      "batch_size": 256})
            .watermark(max_lateness_micros=0)
            .map(lambda c: {"counter": c["counter"],
                            "bucket": c["counter"] % 3}, name="b")
            .key_by("bucket")
            .tumbling_aggregate(100 * 1000,
                                [AggSpec(AggKind.COUNT, None, "cnt")])
            .sink("single_file", {"path": str(out_path)})
        )

    async def run1():
        import arroyo_tpu.config as cfg

        cfg.reset_config()
        ctrl = ControllerServer(InProcessScheduler())
        await ctrl.start()
        job_id = await ctrl.submit_job(build(), job_id="ckpt-stop-job",
                                       checkpoint_url=ckpt_url)
        await ctrl.wait_for_state(job_id, JobState.RUNNING, timeout=30)
        # force an early checkpoint, then stop-with-checkpoint
        job = ctrl.jobs[job_id]
        await asyncio.sleep(0.4)
        await ctrl._trigger_checkpoint(job)
        # wait until that epoch completes at the job level
        for _ in range(200):
            if job.last_successful_epoch:
                break
            await asyncio.sleep(0.05)
        assert job.last_successful_epoch, "checkpoint never completed"
        await ctrl.stop_job(job_id, checkpoint=True)
        state = await ctrl.wait_for_state(job_id, JobState.STOPPED,
                                          timeout=30)
        epoch = job.last_successful_epoch
        await ctrl.scheduler.stop_workers(job_id)
        await ctrl.stop()
        return state, epoch

    state, epoch = asyncio.run(run1())
    assert state == JobState.STOPPED and epoch >= 1

    async def run2():
        ctrl = ControllerServer(InProcessScheduler())
        await ctrl.start()
        job_id = await ctrl.submit_job(build(), job_id="ckpt-stop-job",
                                       checkpoint_url=ckpt_url, restore=True)
        state = await ctrl.wait_for_state(job_id, JobState.FINISHED,
                                          timeout=60)
        await ctrl.scheduler.stop_workers(job_id)
        await ctrl.stop()
        return state

    assert asyncio.run(run2()) == JobState.FINISHED
    rows = [json.loads(l) for l in open(out_path)]
    assert sum(r["cnt"] for r in rows) == 60_000


@pytest.mark.slow
def test_live_rescale_exactly_once(tmp_path):
    """Elastic rescale on a RUNNING cluster: checkpoint-stop, bump
    parallelism 2 -> 3 (state re-sharded by key range), resume, finish —
    output remains exactly-once (states/rescaling.rs path e2e)."""
    out_path = tmp_path / "out.jsonl"
    N = 60_000

    async def scenario():
        ctrl = ControllerServer(InProcessScheduler())
        await ctrl.start()
        prog = (
            Stream.source("impulse", {"event_rate": 15_000.0,
                                      "message_count": N,
                                      "event_time_interval_micros": 1000,
                                      "batch_size": 256}, parallelism=1)
            .watermark(max_lateness_micros=0)
            .map(lambda c: {"counter": c["counter"],
                            "bucket": c["counter"] % 6}, name="b")
            .key_by("bucket")
            .tumbling_aggregate(
                500 * 1000, [AggSpec(AggKind.COUNT, None, "cnt")],
                parallelism=2)
            .sink("single_file", {"path": str(out_path)}, parallelism=1)
        )
        job_id = await ctrl.submit_job(
            prog, checkpoint_url=f"file://{tmp_path}/ckpt", n_workers=1)
        try:
            await ctrl.wait_for_state(job_id, JobState.RUNNING, timeout=30)
            await asyncio.sleep(1.0)  # make mid-stream progress
            agg_ids = [n.operator_id for n in prog.nodes()
                       if "aggregator" in n.operator_id]
            await ctrl.rescale_job(job_id, {agg_ids[0]: 3})
            assert prog.node(agg_ids[0]).parallelism == 3
            state = await ctrl.wait_for_state(job_id, JobState.FINISHED,
                                              timeout=120)
        finally:
            await ctrl.scheduler.stop_workers(job_id)
            await ctrl.stop()
        return state

    state = asyncio.run(scenario())
    assert state == JobState.FINISHED
    rows = [json.loads(line) for line in open(out_path)]
    assert sum(r["cnt"] for r in rows) == N  # exactly-once across rescale
    assert len({r["bucket"] for r in rows}) == 6
