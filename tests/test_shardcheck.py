"""shardcheck: plan-time sharding & transfer verification.

The acceptance contract this file pins:

- shardcheck statically proves ``predicted_reshards == 0`` on the q5,
  q7/q8 join, mesh-sweep and factored correlated-window plans — with
  the ENGINE NEVER STARTED (these tests only plan and analyze);
- the seeded PR 9 funnel (mesh route bits colliding with subtask
  key-range bits) and a sticky string-column mid-chain spec flip are
  both caught at plan time;
- the wiring audit rediscovers the funnel when the real engine source
  has the ``set_route_shift`` call stripped;
- the drift comparator the smoke gate runs fails on static-vs-runtime
  disagreement in BOTH directions;
- ``python -m arroyo_tpu.analysis`` stays green on the repo with the
  new passes armed (zero unwaived findings), and ``--format json``
  serves the machine-readable shape;
- recompile-hazard flags jit cache-key hazards in fixture code while
  the real ops/ + parallel/ layers analyze clean.
"""

import ast
import json
import os
import subprocess
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from arroyo_tpu.analysis import recompile_hazard, shardcheck
from arroyo_tpu.analysis.shardcheck import (
    _SWEEP_SQL,
    analyze,
    check_wiring_source,
    drift_check,
)

WIRING_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "arroyo_tpu", "engine", "operators_window.py")


def _plan(sql: str, parallelism: int = 1):
    from arroyo_tpu.sql import plan_sql

    return plan_sql(sql, parallelism=parallelism)


# ---------------------------------------------------------------------------
# the proof: headline plans carry zero predicted reshards
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("query", ["q5", "q7", "q8"])
@pytest.mark.parametrize("parallelism", [1, 2])
def test_bench_plans_prove_zero_reshards(query, parallelism):
    """The REAL bench plans (the mesh-sweep runs q5 at every width)
    must statically prove the sharded-data-plane invariant on a
    symbolic 8-shard mesh — no engine, no devices, no kernels."""
    import bench

    prog = _plan(bench.QUERIES[query].format(n=1000, b=256), parallelism)
    rep = analyze(prog, nk=8)
    assert rep.predicted_reshards == 0, rep.to_json()
    assert not rep.errors(), [d.render() for d in rep.errors()]


def test_factored_plan_proves_zero_reshards():
    """The factor->derived FORWARD pane edges unify 1:1 (same nk, same
    route shift, equal parallelism): zero predicted reshards, and the
    plan really is factored (one shared pane ring)."""
    from arroyo_tpu.graph.logical import OpKind

    prog = _plan(_SWEEP_SQL["factored"], 1)
    factors = [n for n in prog.nodes()
               if n.operator.kind is OpKind.WINDOW_FACTOR]
    assert len(factors) == 1, "fixture did not factor"
    rep = analyze(prog, nk=8)
    assert rep.predicted_reshards == 0, rep.to_json()
    assert not rep.diagnostics, [d.render() for d in rep.diagnostics]


def test_sweep_plans_clean_at_both_parallelisms():
    for name, sql in _SWEEP_SQL.items():
        for par in (1, 2):
            rep = analyze(_plan(sql, par), nk=8)
            assert not rep.diagnostics and not rep.predicted_reshards, (
                name, par, [d.render() for d in rep.diagnostics])


# ---------------------------------------------------------------------------
# seeded regressions: the PR 9 funnel and the sticky mid-chain flip
# ---------------------------------------------------------------------------


def test_seeded_funnel_caught_statically():
    """Re-create the PR 9 bug class: at parallelism 2 the subtask key
    ranges consume the top hash bit; modeling the broken wiring
    (route shift 0) must flag the route-bit collision — before any
    kernel compiles."""
    prog = _plan(_SWEEP_SQL["q5-shape"], 2)
    rep = analyze(prog, nk=8, assume_route_shift=0)
    errs = [d for d in rep.errors() if d.code == "route-bit-collision"]
    assert errs, [d.render() for d in rep.diagnostics]
    assert "funnel" in errs[0].message
    # the correct wiring (types.route_shift_for) analyzes clean
    assert not analyze(prog, nk=8).errors()


def test_wiring_audit_clean_then_rediscovers_stripped_funnel():
    """The engine half of the contract: the REAL operators_window.py
    wires set_route_shift(route_shift_for(par)); stripping that wiring
    (exactly the PR 9 defect) must be rediscovered by the audit."""
    src = open(WIRING_PATH, encoding="utf-8").read()
    assert check_wiring_source(src, WIRING_PATH) == []
    stripped = "\n".join(
        line for line in src.splitlines()
        if "set_route_shift" not in line and "route_shift_for" not in line)
    findings = check_wiring_source(stripped, WIRING_PATH)
    assert any(f.code == "route-shift-unwired" for f in findings), findings


def test_wiring_audit_rejects_adhoc_shift_expression():
    fixture = (
        "class Op:\n"
        "    def __init__(self):\n"
        "        self.state = make_bin_state(())\n"
        "    def on_start(self, par):\n"
        "        if par > 1:\n"
        "            self.state.set_route_shift((par - 1).bit_length())\n")
    findings = check_wiring_source(fixture, "fixture.py")
    assert any(f.code == "route-shift-contract" for f in findings)


def test_sticky_string_column_mid_chain_flip_caught():
    """A map that introduces a declared string column BETWEEN two keyed
    mesh aggregates pins the second keyed edge to the host route while
    the state upstream is mesh-sharded: the sharding spec flips
    device->host mid-chain — an error at plan time."""
    from arroyo_tpu.graph.logical import AggKind, AggSpec, Stream

    s = (Stream.source("impulse", {"event_rate": 1000.0,
                                   "message_count": 10}, parallelism=2)
         .watermark()
         .key_by("counter")
         .sliding_aggregate(10_000_000, 2_000_000,
                            [AggSpec(AggKind.COUNT, None, "c")],
                            parallelism=2))
    tagged = s.map(lambda c: c, name="tag_it")
    tagged.program.node(tagged.tail).operator.expr.output_schema = {
        "counter": "i", "c": "f", "tag": "s"}
    prog = (tagged.key_by("counter")
            .sliding_aggregate(20_000_000, 4_000_000,
                               [AggSpec(AggKind.SUM, "c", "t")],
                               parallelism=2)
            .sink("blackhole"))
    rep = analyze(prog, nk=8)
    flips = [d for d in rep.errors() if d.code == "sticky-spec-flip"]
    assert flips, [d.render() for d in rep.diagnostics]
    assert "'tag'" in flips[0].message
    # with the mesh off the same plan is merely host-routed: no flip
    assert not analyze(prog, nk=1).errors()


def test_sticky_flip_behind_mesh_join_ring():
    """Join state is mesh-resident too (hot-partition rings spread
    device p % nk): a string column pinning a keyed edge host BEHIND a
    join must flip exactly like the bin-state case."""
    from arroyo_tpu.graph.logical import (
        AggKind,
        AggSpec,
        JoinType,
        Stream,
    )

    left = (Stream.source("impulse", {"event_rate": 1000.0,
                                      "message_count": 10},
                          parallelism=2)
            .watermark()
            .key_by("counter"))
    right = (Stream.source("impulse", {"event_rate": 1000.0,
                                       "message_count": 10},
                           parallelism=2, program=left.program)
             .watermark()
             .key_by("counter"))
    joined = left.join_with_expiration(
        right, 1_000_000, 1_000_000, JoinType.INNER, parallelism=2)
    tagged = joined.map(lambda c: c, name="tag_it")
    tagged.program.node(tagged.tail).operator.expr.output_schema = {
        "counter": "i", "tag": "s"}
    prog = (tagged.key_by("counter")
            .tumbling_aggregate(1_000_000,
                                [AggSpec(AggKind.COUNT, None, "n")],
                                parallelism=2)
            .sink("blackhole"))
    rep = analyze(prog, nk=8)
    assert any(d.code == "sticky-spec-flip" for d in rep.errors()), \
        [d.render() for d in rep.diagnostics]
    # mesh off: the ring never leaves the default device — no flip
    assert not any(d.code == "sticky-spec-flip"
                   for d in analyze(prog, nk=1).errors())


def test_join_declared_string_column_visible_downstream():
    """The planner attaches (name, kind) side schemas to join specs; a
    string column selected THROUGH a join must stay visible to the
    sticky-route checks on the next keyed edge — joins are not a
    schema-laundering point.  Undeclared sides stay unknown (silent)."""
    from arroyo_tpu.graph.logical import AggKind, AggSpec, JoinType, Stream

    def build(left_cols, right_cols):
        left = (Stream.source("impulse", {"event_rate": 1000.0,
                                          "message_count": 10},
                              parallelism=2)
                .watermark()
                .key_by("counter"))
        right = (Stream.source("impulse", {"event_rate": 1000.0,
                                           "message_count": 10},
                               parallelism=2, program=left.program)
                 .watermark()
                 .key_by("counter"))
        joined = left.join_with_expiration(
            right, 1_000_000, 1_000_000, JoinType.INNER, parallelism=2)
        spec = joined.program.node(joined.tail).operator.spec
        spec.left_cols = left_cols
        spec.right_cols = right_cols
        return (joined.key_by("counter")
                .tumbling_aggregate(1_000_000,
                                    [AggSpec(AggKind.COUNT, None, "n")],
                                    parallelism=2)
                .sink("blackhole"))

    prog = build((("counter", "i"),), (("tag", "s"),))
    rep = analyze(prog, nk=8)
    assert any(d.code == "sticky-spec-flip" for d in rep.errors()), \
        [d.render() for d in rep.diagnostics]
    # no declared sides: unknown schema, no findings fabricated
    assert not analyze(build((), ()), nk=8).diagnostics
    # all-numeric sides: proven device-eligible, still clean
    assert not analyze(build((("counter", "i"),), (("v", "f"),)),
                       nk=8).diagnostics


def test_string_payload_column_pins_host_gather(monkeypatch):
    """Payload-plane placement in the spec lattice (PR 15): a string
    column in a join side's declared schema behind mesh-resident key
    rings can never ride the device payload planes.  Under the default
    auto policy that is the designed sticky fallback — a warning
    pointing at the host-gather-share runbook; with device payloads
    FORCED on it is the same device->host mid-chain flip error class
    as a string-pinned keyed edge; with payloads off (or the mesh off)
    there is nothing to flag."""
    from arroyo_tpu.graph.logical import JoinType, Stream

    def build(right_cols):
        left = (Stream.source("impulse", {"event_rate": 1000.0,
                                          "message_count": 10},
                              parallelism=2)
                .watermark()
                .key_by("counter"))
        right = (Stream.source("impulse", {"event_rate": 1000.0,
                                           "message_count": 10},
                               parallelism=2, program=left.program)
                 .watermark()
                 .key_by("counter"))
        joined = left.join_with_expiration(
            right, 1_000_000, 1_000_000, JoinType.INNER, parallelism=2)
        spec = joined.program.node(joined.tail).operator.spec
        spec.left_cols = (("counter", "i"),)
        spec.right_cols = right_cols
        return joined.sink("blackhole")

    prog = build((("tag", "s"),))
    rep = analyze(prog, nk=8)
    assert not rep.errors(), [d.render() for d in rep.errors()]
    warns = [d for d in rep.diagnostics
             if d.code == "payload-host-gather"]
    assert warns and "'tag'" in warns[0].message, \
        [d.render() for d in rep.diagnostics]

    monkeypatch.setenv("ARROYO_JOIN_PAYLOAD_DEVICE", "on")
    errs = [d for d in analyze(prog, nk=8).errors()
            if d.code == "sticky-spec-flip"]
    assert errs and "payload" in errs[0].message, \
        "forced payload residency must escalate to the flip error"

    monkeypatch.setenv("ARROYO_JOIN_PAYLOAD_DEVICE", "off")
    assert not analyze(prog, nk=8).diagnostics, \
        "payloads off: rings are keys-only by design, nothing to flag"

    monkeypatch.delenv("ARROYO_JOIN_PAYLOAD_DEVICE")
    assert not analyze(prog, nk=1).diagnostics, \
        "mesh off: no device rings, no payload placement question"
    # all-numeric sides ride the planes: clean under every policy
    assert not analyze(build((("v", "f"),)), nk=8).diagnostics


def test_long_window_ring_exemption_honors_arroyo_ring(monkeypatch):
    """Long windows (W >= ring_min) ring-shard the BIN axis and skip
    the key-route checks — but ONLY while ARROYO_RING is not forced
    off, mirroring make_bin_state's exact selection: with ring=off the
    same shape is key-routed mesh state and the funnel check applies."""
    from arroyo_tpu.graph.logical import AggKind, AggSpec, Stream

    def plan():
        return (Stream.source("impulse", {"event_rate": 1000.0,
                                          "message_count": 10},
                              parallelism=2)
                .watermark()
                .key_by("counter")
                .sliding_aggregate(300_000_000, 1_000_000,  # W = 300
                                   [AggSpec(AggKind.COUNT, None, "c")],
                                   parallelism=2)
                .sink("blackhole"))

    # ring path: no key route bits, so the seeded-funnel model is inert
    assert not analyze(plan(), nk=8, assume_route_shift=0).errors()
    monkeypatch.setenv("ARROYO_RING", "off")
    errs = analyze(plan(), nk=8, assume_route_shift=0).errors()
    assert any(d.code == "route-bit-collision" for d in errs), \
        [d.render() for d in errs]


def test_sticky_host_edge_warns_without_mesh_state_behind():
    """A string GROUP BY key straight off the source is stable (host
    from batch 0) — a warning, not an error, and the plan still
    predicts zero reshards."""
    sql = """
CREATE TABLE nexmark WITH (
  connector = 'nexmark', event_rate = '1000', num_events = '1000',
  rate_limited = 'false', batch_size = '256'
);
SELECT bid.channel as channel, TUMBLE(INTERVAL '2' SECOND) as window,
       count(*) AS num
FROM nexmark WHERE bid is not null GROUP BY 1, 2
"""
    rep = analyze(_plan(sql, 2), nk=8)
    assert not rep.errors()
    assert rep.predicted_reshards == 0
    warns = [d for d in rep.diagnostics if d.code == "sticky-host-edge"]
    assert warns and "'channel'" in warns[0].message


def test_merge_cols_string_wins_on_conflict():
    """Merging branch schemas where a column is a string on ANY branch
    must keep the string kind visible — the sticky host route is forced
    at runtime whenever string values appear, so a conflicting merge
    can never launder a column into device-provable; numeric-vs-numeric
    conflicts promote on device and honestly stay '?'."""
    from arroyo_tpu.analysis.shardcheck import _has_string, _merge_cols

    merged, is_open = _merge_cols([({"k": "i", "tag": "s"}, False),
                                   ({"k": "i", "tag": "i"}, False)])
    assert merged["tag"] == "s" and _has_string(merged) == "tag"
    assert not is_open
    merged2, _ = _merge_cols([({"v": "i"}, False), ({"v": "f"}, False)])
    assert merged2["v"] == "?" and _has_string(merged2) is None


def test_shuffled_pane_edge_predicts_reshard():
    """Mutating the factored plan so the factor's pane arrays cross a
    repartition point must predict reshards (> 0) and reject."""
    from arroyo_tpu.graph.logical import EdgeType, OpKind

    prog = _plan(_SWEEP_SQL["factored"], 1)
    mutated = 0
    for u, _v, data in prog.graph.edges(data=True):
        if prog.node(u).operator.kind is OpKind.WINDOW_FACTOR:
            data["edge"].typ = EdgeType.SHUFFLE
            mutated += 1
    assert mutated, "fixture did not factor"
    rep = analyze(prog, nk=8)
    assert rep.predicted_reshards >= mutated
    assert any(d.code == "predicted-reshard" for d in rep.errors())


def test_unpinned_spec_flagged_on_rebalanced_keyed_state():
    """A FORWARD edge into keyed state (the dropped-shuffle mutation
    class) is an unpinned-spec entry: the kernel would implicitly
    re-key every batch."""
    from arroyo_tpu.graph.logical import AggKind, AggSpec, EdgeType, \
        Stream

    prog = (Stream.source("impulse", {"event_rate": 1000.0,
                                      "message_count": 10},
                          parallelism=2)
            .watermark()
            .key_by("counter")
            .tumbling_aggregate(1_000_000,
                                [AggSpec(AggKind.COUNT, None, "c")])
            .sink("blackhole"))
    for _u, _v, data in prog.graph.edges(data=True):
        if data["edge"].typ is EdgeType.SHUFFLE:
            data["edge"].typ = EdgeType.FORWARD
    rep = analyze(prog, nk=8)
    assert any(d.code == "shard-unpinned" for d in rep.errors())


# ---------------------------------------------------------------------------
# the drift gate comparator
# ---------------------------------------------------------------------------


def test_drift_check_fails_both_directions():
    assert drift_check(0, 0) is None
    assert drift_check(3, 3) is None
    rot = drift_check(0, 2, "q5")
    assert rot is not None and "model" in rot and "q5" in rot
    pessimist = drift_check(2, 0, "q5")
    assert pessimist is not None and rot != pessimist


# ---------------------------------------------------------------------------
# validator-consumer wiring
# ---------------------------------------------------------------------------


def test_plan_report_carries_predicted_reshards():
    from arroyo_tpu.analysis.plan_validator import plan_report

    rep = plan_report(_plan(_SWEEP_SQL["q5-shape"], 1))
    assert rep["predicted_reshards"] == 0
    assert isinstance(rep["mesh_shards"], int)


def test_plan_report_null_when_verifier_disabled(monkeypatch):
    """ARROYO_SHARDCHECK=0 must report null, never a fabricated 0 — a
    console or bench line must not display 'statically proven clean'
    for a plan nobody verified."""
    from arroyo_tpu.analysis.plan_validator import plan_report

    monkeypatch.setenv("ARROYO_SHARDCHECK", "0")
    rep = plan_report(_plan(_SWEEP_SQL["q5-shape"], 1))
    assert rep["predicted_reshards"] is None
    assert rep["mesh_shards"] is None


def test_repo_pass_findings_honor_inline_waivers(tmp_path):
    """A wiring-audit finding anchored to a parsed file picks up that
    file's inline waiver exactly like AST-pass findings (the documented
    waiver contract covers the repo pass)."""
    from arroyo_tpu.analysis.core import run_analysis, unwaived

    pkg = tmp_path / "arroyo_tpu" / "engine"
    pkg.mkdir(parents=True)
    wiring = pkg / "operators_window.py"
    wiring.write_text(
        "class Op:\n"
        "    def __init__(self):\n"
        "        # arroyolint: disable=shardcheck -- fixture: wiring "
        "intentionally absent\n"
        "        self.state = make_bin_state(())\n")
    findings = run_analysis(paths=[str(wiring)], baseline_path=None,
                            passes=["shardcheck"],
                            repo_root=str(tmp_path))
    audit = [f for f in findings if f.code == "route-shift-unwired"]
    assert audit and audit[0].waived, [f.render() for f in findings]
    assert not [f for f in unwaived(findings)
                if f.code == "route-shift-unwired"]


def test_repo_pass_waivers_honor_relative_paths(tmp_path, monkeypatch):
    """Same contract under the documented CLI form: a RELATIVE path on
    the command line still lands the repo-pass finding on that file's
    inline waivers (the audit anchors findings at absolute paths; the
    lookup must normalize both sides)."""
    from arroyo_tpu.analysis.core import run_analysis, unwaived

    pkg = tmp_path / "arroyo_tpu" / "engine"
    pkg.mkdir(parents=True)
    wiring = pkg / "operators_window.py"
    wiring.write_text(
        "class Op:\n"
        "    def __init__(self):\n"
        "        # arroyolint: disable=shardcheck -- fixture: wiring "
        "intentionally absent\n"
        "        self.state = make_bin_state(())\n")
    monkeypatch.chdir(tmp_path)
    findings = run_analysis(
        paths=[os.path.join("arroyo_tpu", "engine",
                            "operators_window.py")],
        baseline_path=None, passes=["shardcheck"],
        repo_root=str(tmp_path))
    audit = [f for f in findings if f.code == "route-shift-unwired"]
    assert audit and audit[0].waived, [f.render() for f in findings]
    assert not [f for f in unwaived(findings)
                if f.code == "route-shift-unwired"]


def test_single_file_lint_skips_plan_sweep():
    """A lint restricted below the package root must not pay (or gate
    on) the representative-plan sweep — only whole-package invocations
    run it; the wiring audit itself still runs either way."""
    from arroyo_tpu.analysis import core

    findings = core.run_analysis(
        paths=[os.path.join(core.PKG_ROOT, "analysis", "core.py")],
        baseline_path=None, passes=["shardcheck"])
    assert not [f for f in findings if "plan sweep" in f.message], \
        [f.render() for f in findings]


def test_check_program_rejects_flip_plan_and_escape_hatch(monkeypatch):
    """Engine build preflight (validate_before_build -> check_program)
    rejects the sticky-flip plan with shardcheck armed and admits it
    with ARROYO_SHARDCHECK=0 — the engine is never constructed."""
    from arroyo_tpu.analysis.plan_validator import PlanValidationError
    from arroyo_tpu.engine.build import validate_before_build
    from arroyo_tpu.graph.logical import AggKind, AggSpec, Stream
    from arroyo_tpu.parallel.mesh_window import mesh_key_shards

    if mesh_key_shards() < 2:
        pytest.skip("needs the suite's multi-device mesh")
    s = (Stream.source("impulse", {"event_rate": 1000.0,
                                   "message_count": 10}, parallelism=2)
         .watermark()
         .key_by("counter")
         .sliding_aggregate(10_000_000, 2_000_000,
                            [AggSpec(AggKind.COUNT, None, "c")],
                            parallelism=2))
    tagged = s.map(lambda c: c, name="tag_it")
    tagged.program.node(tagged.tail).operator.expr.output_schema = {
        "counter": "i", "c": "f", "tag": "s"}
    prog = (tagged.key_by("counter")
            .sliding_aggregate(20_000_000, 4_000_000,
                               [AggSpec(AggKind.SUM, "c", "t")],
                               parallelism=2)
            .sink("blackhole"))
    with pytest.raises(PlanValidationError) as ei:
        validate_before_build(prog)
    assert any(d.code == "sticky-spec-flip" for d in ei.value.diagnostics)
    monkeypatch.setenv("ARROYO_SHARDCHECK", "0")
    validate_before_build(prog)  # escape hatch admits it


def test_rest_validate_serves_predicted_reshards(run_async):
    """The REST validate response carries the plan report fields in the
    same structured-diagnostics shape the console renders."""
    import httpx

    from arroyo_tpu.api.rest import ApiServer
    from arroyo_tpu.controller.controller import ControllerServer

    async def scenario():
        controller = ControllerServer()
        await controller.start()
        api = ApiServer(controller)
        port = await api.start()
        try:
            async with httpx.AsyncClient(
                    base_url=f"http://127.0.0.1:{port}",
                    timeout=30) as c:
                r = await c.post("/v1/pipelines/validate", json={
                    "query": "CREATE TABLE imp WITH "
                             "(connector='impulse', event_rate='100', "
                             "message_count='10');"
                             "SELECT count(*) as c, "
                             "TUMBLE(INTERVAL '1' SECOND) as w "
                             "FROM imp GROUP BY 2"})
                assert r.status_code == 200, r.text
                out = r.json()
                assert out["predicted_reshards"] == 0
                assert out["mesh_shards"] >= 1
                assert not [d for d in out["diagnostics"]
                            if d["severity"] == "error"], out
        finally:
            await api.stop()
            await controller.stop()

    run_async(scenario())


def test_bench_preflight_returns_prediction():
    import bench

    prog = _plan(bench.QUERIES["q5"].format(n=1000, b=256), 1)
    assert bench.preflight_validate(prog, "test_metric") == 0


# ---------------------------------------------------------------------------
# lint integration: repo pass + CLI + --format json
# ---------------------------------------------------------------------------


def test_repo_pass_zero_unwaived_findings():
    """Clean-repo acceptance: the shardcheck + recompile-hazard passes
    report zero unwaived findings over the checked-in tree."""
    from arroyo_tpu.analysis.core import run_analysis, unwaived

    findings = run_analysis(passes=["shardcheck", "recompile-hazard"])
    bad = unwaived(findings)
    assert not bad, [f.render() for f in bad]


def test_cli_format_json_machine_readable():
    from arroyo_tpu.analysis import core

    r = subprocess.run(
        [sys.executable, "-m", "arroyo_tpu.analysis", "--format", "json",
         "--pass", "recompile-hazard", "--all",
         os.path.join("arroyo_tpu", "ops")],
        capture_output=True, text=True, cwd=core.REPO_ROOT)
    assert r.returncode == 0, r.stdout + r.stderr
    out = json.loads(r.stdout)
    assert out["version"] == 1
    assert "counts" in out and out["counts"]["gate"] == 0
    for f in out["findings"]:
        assert {"file", "line", "pass", "code", "fingerprint"} <= set(f)


# ---------------------------------------------------------------------------
# recompile-hazard pass
# ---------------------------------------------------------------------------


_HAZARD_FIXTURE = '''
import functools, jax

def hot(batch):
    @jax.jit
    def step(x):
        return x + 1
    return step(batch)

@functools.lru_cache(maxsize=8)
def factory(n):
    @jax.jit
    def run(x):
        if x.shape[0] > 4:
            return x
        return -x
    return run

def caller(batch):
    f = factory(len(batch))
    g = factory([1, 2])
    return f(batch)

class Op:
    def get(self, key):
        f = self._cache.get(key)
        if f is None:
            @jax.jit
            def run(x):
                return x * 2
            self._cache[key] = run
            f = run
        return f
'''


def test_recompile_hazard_fixture_rules():
    findings = recompile_hazard.check(
        ast.parse(_HAZARD_FIXTURE), _HAZARD_FIXTURE.splitlines(),
        "ops/fixture.py", force=True)
    codes = sorted(f.code for f in findings)
    assert codes == ["jit-rebuild", "shape-branch", "unhashable-static",
                     "varying-static"], [f.render() for f in findings]
    # the cache-store pattern (class Op.get) is NOT a rebuild: exactly
    # one rebuild finding, anchored at hot()'s inline jit
    rebuilds = [f for f in findings if f.code == "jit-rebuild"]
    assert len(rebuilds) == 1
    assert "hot()" in rebuilds[0].message


def test_recompile_hazard_flags_keyword_args():
    """The cached-factory scan covers keyword arguments too — the
    kwarg spelling of a varying/unhashable cache key is the same
    compile-storm/TypeError class as the positional one."""
    src = (
        "import functools\n"
        "@functools.lru_cache(maxsize=8)\n"
        "def factory(n, dims=()):\n"
        "    pass\n"
        "def hot(batch):\n"
        "    factory(n=len(batch))\n"
        "    factory(1, dims=[1, 2])\n")
    findings = recompile_hazard.check(
        ast.parse(src), src.splitlines(), "ops/fixture.py", force=True)
    codes = sorted(f.code for f in findings)
    assert codes == ["unhashable-static", "varying-static"], \
        [f.render() for f in findings]


def test_recompile_hazard_repo_layers_clean():
    import glob

    root = os.path.dirname(WIRING_PATH).replace(
        os.path.join("arroyo_tpu", "engine"), "arroyo_tpu")
    for sub in ("ops", "parallel"):
        for path in sorted(glob.glob(os.path.join(root, sub, "*.py"))):
            src = open(path, encoding="utf-8").read()
            findings = recompile_hazard.check(
                ast.parse(src), src.splitlines(), path)
            assert not findings, [f.render() for f in findings]


# ---------------------------------------------------------------------------
# session run state (PR 19): plan-time placement + the sticky host fire
# ---------------------------------------------------------------------------


def _session_udaf_plan(agg_arg: str):
    """config5-shape session plan with a UDAF over ``agg_arg`` (a
    string column for 'name', numeric for 'v')."""
    import numpy as np

    from arroyo_tpu import Batch
    from arroyo_tpu.sql import SchemaProvider, plan_sql, unregister_udfs

    unregister_udfs()
    sec = 1_000_000
    p = SchemaProvider()
    rng = np.random.default_rng(7)
    n = 32
    ts = np.sort(rng.integers(0, 3 * sec, n)).astype(np.int64)
    p.add_memory_table("events", {"k": "i", "v": "f", "name": "s"}, [
        Batch(ts, {"k": rng.integers(0, 4, n).astype(np.int64),
                   "v": rng.random(n).astype(np.float64),
                   "name": np.array(["u"] * n, dtype=object)})])
    p.register_udaf("agg_fn", lambda vals: 0.0)
    return plan_sql(
        "CREATE TABLE out WITH (connector='memory', name='results'); "
        f"INSERT INTO out SELECT k, agg_fn({agg_arg}) as a, count(*) as c "
        "FROM events GROUP BY k, session(interval '1 second')", p)


def test_session_string_udaf_warns_host_aggregate(monkeypatch):
    """A string column feeding a session-window UDAF behind device
    session runs is the designed sticky host fallback: interval merges
    ride the device union kernel but every fire replays the per-segment
    host loop.  shardcheck surfaces it as the session analog of
    payload-host-gather; under ARROYO_SESSION_STATE=legacy everything
    is host by design and the finding is suppressed.  A numeric UDAF
    arg stays clean (it either compiles to channels or host-loops over
    f64 rows that pack fine)."""
    from arroyo_tpu.sql import unregister_udfs

    monkeypatch.delenv("ARROYO_SESSION_STATE", raising=False)
    try:
        prog = _session_udaf_plan("name")
        rep = analyze(prog, nk=8)
        assert not rep.errors(), [d.render() for d in rep.errors()]
        assert not rep.predicted_reshards
        warns = [d for d in rep.diagnostics
                 if d.code == "session-host-aggregate"]
        assert warns and "'__ain0'" in warns[0].message, \
            [d.render() for d in rep.diagnostics]

        monkeypatch.setenv("ARROYO_SESSION_STATE", "legacy")
        assert not [d for d in analyze(prog, nk=8).diagnostics
                    if d.code == "session-host-aggregate"], \
            "legacy session state is all-host by design: nothing to flag"
        monkeypatch.delenv("ARROYO_SESSION_STATE")

        clean = analyze(_session_udaf_plan("v"), nk=8)
        assert not [d for d in clean.diagnostics
                    if d.code == "session-host-aggregate"], \
            [d.render() for d in clean.diagnostics]
    finally:
        unregister_udfs()


def test_sessions_sweep_shape_registered():
    """The repo-level plan sweep carries a config5-shape session
    window: the device session-run placement must prove out at zero
    errors / zero predicted reshards just like the hop and join shapes
    (test_sweep_plans_clean_at_both_parallelisms iterates the dict, so
    this only pins that the shape is actually IN the sweep)."""
    assert "sessions" in _SWEEP_SQL
    assert "session(INTERVAL" in _SWEEP_SQL["sessions"]
