"""SQL frontend tests: planning of the reference's test-suite query shapes
(arroyo-sql-testing/src/full_query_tests.rs) and execution correctness over
in-memory tables (the correctness_run_codegen analog)."""

import numpy as np
import pytest

from arroyo_tpu import Batch
from arroyo_tpu.connectors.memory import clear_sink, sink_output
from arroyo_tpu.engine.engine import LocalRunner
from arroyo_tpu.sql import SchemaProvider, plan_sql

SEC = 1_000_000


def run_sql(sql, provider=None):
    clear_sink("results")
    prog = plan_sql(sql, provider)
    LocalRunner(prog).run()
    outs = sink_output("results")
    return Batch.concat(outs) if outs else None


def events_table(provider, n=200, n_keys=5, span=4 * SEC):
    rng = np.random.default_rng(7)
    ts = np.sort(rng.integers(0, span, n)).astype(np.int64)
    provider.add_memory_table("events", {"k": "i", "v": "i", "name": "s"}, [
        Batch(ts, {
            "k": rng.integers(0, n_keys, n).astype(np.int64),
            "v": rng.integers(1, 50, n).astype(np.int64),
            "name": np.array(
                [f"name{i % 3}" for i in range(n)], dtype=object),
        })
    ])
    return provider


# -- planning tests (full_pipeline_codegen analog: plan must succeed) --------


PLAN_QUERIES = [
    ("select_star", "SELECT * FROM nexmark"),
    ("bid_fields", "SELECT bid.auction as auction, bid.price as price "
                   "FROM nexmark WHERE bid is not null"),
    ("tumbling_count",
     "SELECT count(*), auction.id FROM nexmark WHERE auction is not null "
     "GROUP BY tumble(interval '2 second'), auction.id"),
    ("sliding_count_distinct",
     """WITH bids as (
       SELECT bid.auction as auction, bid.bidder as bidder,
              bid.datetime as datetime FROM nexmark where bid is not null)
     SELECT * FROM (
     SELECT bidder, COUNT(distinct auction) as distinct_auctions
     FROM bids B1
     GROUP BY bidder, HOP(INTERVAL '3 second', INTERVAL '10' minute))
     WHERE distinct_auctions > 2"""),
    ("query_5_join",
     """WITH bids as (SELECT bid.auction as auction, bid.datetime as datetime
        FROM (select bid from nexmark) where bid is not null)
        SELECT AuctionBids.auction as auction, AuctionBids.num as count
        FROM (
          SELECT B1.auction, HOP(INTERVAL '2' SECOND, INTERVAL '10' SECOND)
                 as window, count(*) AS num
          FROM bids B1 GROUP BY 1, 2
        ) AS AuctionBids
        JOIN (
          SELECT max(num) AS maxn, window
          FROM (
            SELECT count(*) AS num,
                   HOP(INTERVAL '2' SECOND, INTERVAL '10' SECOND) AS window
            FROM bids B2 GROUP BY B2.auction, 2
          ) AS CountBids
          GROUP BY 2
        ) AS MaxBids
        ON AuctionBids.num = MaxBids.maxn
           and AuctionBids.window = MaxBids.window"""),
    ("inner_join",
     """SELECT * FROM (SELECT bid.auction as auction, bid.price as price
        FROM nexmark WHERE bid is not null) bids
        JOIN (SELECT auction.id as id, auction.initial_bid as initial_bid
        FROM nexmark where auction is not null) auctions
        on bids.auction = auctions.id"""),
    ("session_window",
     "SELECT count(*), session(INTERVAL '10' SECOND) AS window "
     "from nexmark group by window, auction.id"),
    ("count_over_case",
     "SELECT count(case when bid.price > 100 then 1 else null end) as big "
     "from nexmark group by tumble(interval '1 second')"),
    ("filter_on_updating_aggregates",
     """SELECT auction / 2 as half_auction FROM (
        SELECT auction FROM (
          SELECT count(*) as bids, bid.auction as auction from nexmark
          where bid is not null GROUP BY 2
        ) WHERE bids > 1 and bids < 10
     ) WHERE auction % 2 = 0"""),
    ("cast_bug", "SELECT CAST(1 as FLOAT) from nexmark"),
    ("create_table_insert",
     """CREATE TABLE sink_t (total bigint) WITH (
          connector = 'blackhole', type = 'sink');
        INSERT INTO sink_t SELECT count(*) FROM nexmark
        GROUP BY tumble(interval '1 second')"""),
    ("virtual_field",
     """create table demo_stream (
          ts BIGINT NOT NULL,
          event_time TIMESTAMP GENERATED ALWAYS AS
            (CAST(from_unixtime(ts * 1000000000) as TIMESTAMP))
        ) WITH (
          connector = 'impulse', type = 'source',
          event_time_field = 'event_time'
        );
        select * from demo_stream"""),
]


@pytest.mark.parametrize("name,sql", PLAN_QUERIES,
                         ids=[n for n, _ in PLAN_QUERIES])
def test_plan(name, sql):
    prog = plan_sql(sql)
    assert prog.graph.number_of_nodes() >= 3
    assert not prog.validate()


# -- execution tests ---------------------------------------------------------


def test_exec_projection_filter():
    p = events_table(SchemaProvider())
    out = run_sql("SELECT k, v * 2 as v2 FROM events WHERE v > 25", p)
    assert out is not None
    assert np.all(out.columns["v2"] > 50)
    assert np.all(out.columns["v2"] % 2 == 0)


def test_exec_tumbling_group_by():
    p = events_table(SchemaProvider())
    out = run_sql(
        "SELECT k, count(*) as cnt, sum(v) as total FROM events "
        "GROUP BY k, tumble(interval '1 second')", p)
    assert out is not None
    assert int(out.columns["cnt"].sum()) == 200
    # cross-check sum per key against numpy
    src = sink_output  # noqa: F841
    assert "window_start" in out.columns and "window_end" in out.columns


def test_exec_case_count():
    p = events_table(SchemaProvider())
    out = run_sql(
        "SELECT count(case when v > 25 then 1 else null end) as big, "
        "count(*) as total FROM events GROUP BY tumble(interval '2 second')",
        p)
    assert int(out.columns["total"].sum()) == 200
    assert 0 < int(out.columns["big"].sum()) < 200


def test_exec_avg_min_max():
    p = events_table(SchemaProvider())
    out = run_sql(
        "SELECT k, avg(v) as a, min(v) as lo, max(v) as hi FROM events "
        "GROUP BY k, tumble(interval '4 second')", p)
    assert np.all(out.columns["lo"] <= out.columns["a"])
    assert np.all(out.columns["a"] <= out.columns["hi"])


def test_exec_updating_aggregate_filter():
    p = events_table(SchemaProvider())
    out = run_sql(
        "SELECT k2 FROM (SELECT count(*) as c, k as k2 FROM events GROUP BY 2)"
        " WHERE c > 30", p)
    assert out is not None and len(out) > 0


def test_exec_string_function():
    p = events_table(SchemaProvider())
    out = run_sql("SELECT upper(name) as uname, k FROM events", p)
    assert set(np.unique(list(out.columns["uname"]))) == {
        "NAME0", "NAME1", "NAME2"}


def test_exec_join():
    p = SchemaProvider()
    lts = np.array([100, 200, 300], dtype=np.int64)
    p.add_memory_table("l", {"id": "i", "lv": "i"}, [
        Batch(lts, {"id": np.array([1, 2, 3], dtype=np.int64),
                    "lv": np.array([10, 20, 30], dtype=np.int64)})])
    p.add_memory_table("r", {"id": "i", "rv": "i"}, [
        Batch(lts, {"id": np.array([2, 3, 4], dtype=np.int64),
                    "rv": np.array([200, 300, 400], dtype=np.int64)})])
    out = run_sql("SELECT l.id as id, l.lv as lv, r.rv as rv FROM l "
                  "JOIN r ON l.id = r.id", p)
    pairs = sorted(zip(out.columns["lv"].tolist(), out.columns["rv"].tolist()))
    assert pairs == [(20, 200), (30, 300)]


def _updating_net(out, cols):
    """Apply __op retractions (CREATE/UPDATE add, DELETE remove) to get the
    NET row multiset of an updating stream's output."""
    from collections import Counter

    net = Counter()
    ops = out.columns["__op"]
    for j in range(len(out.timestamp)):
        row = tuple(None if (isinstance(out.columns[c][j], float)
                             and np.isnan(out.columns[c][j]))
                    else out.columns[c][j].item()
                    if hasattr(out.columns[c][j], "item")
                    else out.columns[c][j]
                    for c in cols)
        if int(ops[j]) == 2:  # DELETE
            net[row] -= 1
            if net[row] == 0:
                del net[row]
        else:
            net[row] += 1
    return net


def _join_tables(p, r_ids=(1, 2), r_vals=(111, 222)):
    p.add_memory_table("l", {"id": "i", "lv": "i"}, [
        Batch(np.array([100, 200, 300], dtype=np.int64),
              {"id": np.array([1, 2, 3], dtype=np.int64),
               "lv": np.array([10, 20, 30], dtype=np.int64)})])
    p.add_memory_table("r", {"id": "i", "rv": "i"}, [
        Batch(np.array([150, 250], dtype=np.int64),
              {"id": np.array(r_ids, dtype=np.int64),
               "rv": np.array(r_vals, dtype=np.int64)})])
    return p


def test_exec_left_join_unmatched_rows_survive():
    """The VERDICT repro: a 3-row LEFT JOIN with one unmatched left row
    must net 3 rows — the unmatched row with a NULL right side — via
    __op retraction semantics (join_with_expiration.rs:46-95)."""
    p = _join_tables(SchemaProvider())
    out = run_sql("SELECT l.id as id, lv, rv FROM l "
                  "LEFT JOIN r ON l.id = r.id", p)
    assert "__op" in out.columns  # outer joins are updating streams
    net = _updating_net(out, ("id", "lv", "rv"))
    assert net == {(1, 10, 111): 1, (2, 20, 222): 1, (3, 30, None): 1}


def test_exec_left_join_late_match_retracts():
    """When the first right row for a key arrives AFTER the padded left
    emission, the padded row is retracted (DELETE) and replaced — the
    reference's UpdatingData::Update (join_with_expiration.rs:80-95)."""
    p = _join_tables(SchemaProvider())
    out = run_sql("SELECT l.id as id, lv, rv FROM l "
                  "LEFT JOIN r ON l.id = r.id", p)
    ops = out.columns["__op"].astype(int).tolist()
    # the memory sources race, but whenever a padded row was emitted for a
    # key that later matched, a DELETE for it must also appear
    rows = list(zip(out.columns["id"].tolist(), ops))
    padded_created = {int(i) for (i, o), j in zip(rows, range(len(rows)))
                      if o == 0 and isinstance(out.columns["rv"][j], float)
                      and np.isnan(out.columns["rv"][j]) and int(i) in (1, 2)}
    deleted = {int(i) for i, o in rows if o == 2}
    assert padded_created == deleted


def test_exec_right_and_full_join():
    p = _join_tables(SchemaProvider(), r_ids=(2, 4), r_vals=(222, 444))
    out = run_sql("SELECT l.id as lid, r.id as rid, lv, rv FROM l "
                  "RIGHT JOIN r ON l.id = r.id", p)
    net = _updating_net(out, ("lid", "rid", "lv", "rv"))
    assert net == {(2, 2, 20, 222): 1, (None, 4, None, 444): 1}

    p = _join_tables(SchemaProvider(), r_ids=(2, 4), r_vals=(222, 444))
    out = run_sql("SELECT l.id as lid, r.id as rid, lv, rv FROM l "
                  "FULL JOIN r ON l.id = r.id", p)
    net = _updating_net(out, ("lid", "rid", "lv", "rv"))
    assert net == {(1, None, 10, None): 1, (2, 2, 20, 222): 1,
                   (3, None, 30, None): 1, (None, 4, None, 444): 1}


def test_exec_windowed_left_join_pads_appended():
    """Windowed outer join: unmatched side null-padded per fired window,
    append-only (each window fires once -> no retractions), matching the
    reference's list-merge codegen (expressions.rs:134-230)."""
    p = SchemaProvider()
    SEC = 1_000_000
    p.add_memory_table("a", {"u": "i"}, [
        Batch(np.array([1 * SEC, 2 * SEC], dtype=np.int64),
              {"u": np.array([1, 2], dtype=np.int64)})])
    p.add_memory_table("b", {"s": "i"}, [
        Batch(np.array([1 * SEC + 1000], dtype=np.int64),
              {"s": np.array([1], dtype=np.int64)})])
    out = run_sql("""
      SELECT P.u as u, P.np as np, A.na as na
      FROM (SELECT u, TUMBLE(INTERVAL '1' SECOND) as window, count(*) as np
            FROM a GROUP BY 1, 2) AS P
      LEFT JOIN (SELECT s, TUMBLE(INTERVAL '1' SECOND) as window,
                        count(*) as na
                 FROM b GROUP BY 1, 2) AS A
      ON P.u = A.s and P.window = A.window
    """, p)
    assert "__op" not in out.columns  # append-only
    got = {}
    for j in range(len(out.timestamp)):
        na = out.columns["na"][j]
        got[int(out.columns["u"][j])] = (
            int(out.columns["np"][j]),
            None if np.isnan(na) else int(na))
    assert got == {1: (1, 1), 2: (1, None)}


def test_plan_rejects_aggregate_over_outer_join():
    from arroyo_tpu.sql import SqlPlanError

    p = _join_tables(SchemaProvider())
    with pytest.raises(SqlPlanError, match="updating stream"):
        plan_sql("SELECT count(*) as c FROM "
                 "(SELECT l.id as id, lv, rv FROM l "
                 " LEFT JOIN r ON l.id = r.id) GROUP BY id", p)


def test_plan_rejects_updating_misuse():
    """Updating streams (__op retraction rows) may not silently feed
    operators that would treat DELETE rows as data: joins, UNION ALL
    with an append-only branch, and TopN all reject at plan time."""
    from arroyo_tpu.sql import SqlPlanError

    p = _join_tables(SchemaProvider())
    p.add_memory_table("t2", {"id": "i", "tv": "i"}, [
        Batch(np.array([100], dtype=np.int64),
              {"id": np.array([1], dtype=np.int64),
               "tv": np.array([7], dtype=np.int64)})])
    outer = "(SELECT l.id as id, lv, rv FROM l LEFT JOIN r ON l.id = r.id)"
    with pytest.raises(SqlPlanError, match="updating stream"):
        plan_sql(f"SELECT s.id as sid, tv FROM {outer} AS s "
                 "JOIN t2 ON s.id = t2.id", p)
    with pytest.raises(SqlPlanError, match="both"):
        plan_sql(f"SELECT id, lv, rv FROM {outer} UNION ALL "
                 "SELECT id, tv as lv, tv as rv FROM t2", p)
    with pytest.raises(SqlPlanError, match="updating stream"):
        plan_sql(f"SELECT id, lv, rv FROM {outer} "
                 "ORDER BY lv DESC LIMIT 2", p)


def test_exec_count_distinct():
    p = SchemaProvider()
    ts = np.arange(6, dtype=np.int64) * 100
    p.add_memory_table("t", {"k": "i", "x": "i"}, [
        Batch(ts, {"k": np.array([1, 1, 1, 2, 2, 2], dtype=np.int64),
                   "x": np.array([5, 5, 6, 7, 8, 9], dtype=np.int64)})])
    out = run_sql("SELECT k, count(distinct x) as dx FROM t "
                  "GROUP BY k, tumble(interval '1 second')", p)
    got = {int(out.columns["k"][i]): int(out.columns["dx"][i])
           for i in range(len(out))}
    assert got == {1: 2, 2: 3}


def test_exec_nexmark_q5_shape():
    """Run the q5 hot-items query end-to-end on a small nexmark stream."""
    sql = """
    CREATE TABLE nexmark WITH (
      connector = 'nexmark', event_rate = '50000', runtime_secs = '0.2',
      rate_limited = 'false'
    );
    WITH bids as (SELECT bid.auction as auction, bid.datetime as datetime
        FROM nexmark where bid is not null)
    SELECT AuctionBids.auction as auction, AuctionBids.num as num
    FROM (
      SELECT B1.auction, HOP(INTERVAL '2' SECOND, INTERVAL '10' SECOND)
             as window, count(*) AS num
      FROM bids B1 GROUP BY 1, 2
    ) AS AuctionBids
    JOIN (
      SELECT max(num) AS maxn, window
      FROM (
        SELECT count(*) AS num,
               HOP(INTERVAL '2' SECOND, INTERVAL '10' SECOND) AS window
        FROM bids B2 GROUP BY B2.auction, 2
      ) AS CountBids
      GROUP BY 2
    ) AS MaxBids
    ON AuctionBids.num = MaxBids.maxn and AuctionBids.window = MaxBids.window
    """
    clear_sink("results")
    prog = plan_sql(sql)
    LocalRunner(prog).run()
    outs = sink_output("results")
    assert outs, "q5 produced no output"
    out = Batch.concat(outs)
    assert len(out) > 0
    assert np.all(out.columns["num"] >= 1)


def test_exec_group_by_window_consolidates_refinements():
    """GROUP BY the window of a windowed input (q5's MaxBids shape) must
    emit exactly ONE final row per window — even at parallelism > 1,
    where one window's partial rows arrive in several batches from
    several upstream subtasks.  The stale partial-aggregate rows that an
    eager updating aggregate would leak (advisor r3 medium finding) must
    be consolidated before emission."""
    import collections

    from arroyo_tpu.sql.planner import Planner

    rng = np.random.default_rng(11)
    n = 6000
    ts = np.sort(rng.integers(0, 6 * SEC, n)).astype(np.int64)
    keys = rng.integers(0, 12, n).astype(np.int64)
    provider = SchemaProvider()
    provider.add_memory_table("events", {"k": "i"}, [
        Batch(ts[i:i + 500], {"k": keys[i:i + 500]})
        for i in range(0, n, 500)])
    clear_sink("results")
    prog = Planner(provider).plan("""
        SELECT max(num) AS maxn, window FROM (
          SELECT count(*) AS num, TUMBLE(INTERVAL '2' SECOND) AS window
          FROM events GROUP BY k, 2
        ) GROUP BY 2
    """, query_parallelism=2)
    LocalRunner(prog).run()
    out = Batch.concat(sink_output("results"))
    per_w = collections.Counter(int(w) for w in out.columns["window_end"])
    assert all(v == 1 for v in per_w.values()), per_w
    want = collections.defaultdict(collections.Counter)
    for t, k in zip(ts.tolist(), keys.tolist()):
        wend = (t // (2 * SEC) + 1) * 2 * SEC
        want[wend][k] += 1
    assert set(per_w) == set(want)
    got = {int(w): int(m) for w, m in zip(out.columns["window_end"],
                                          out.columns["maxn"])}
    for wend, cnt in want.items():
        assert got[wend] == max(cnt.values()), (wend, got[wend], cnt)


def test_exec_nullable_bool_predicate():
    """Object-dtype nullable bool columns (JSON rows with missing fields)
    must evaluate in predicates: None -> not matched, not a crash."""
    from arroyo_tpu.sql.schema_provider import SchemaProvider
    from arroyo_tpu.sql.planner import Planner
    from arroyo_tpu.engine.engine import LocalRunner
    from arroyo_tpu.connectors.memory import clear_sink, sink_output

    provider = SchemaProvider()
    n = 9
    ts = np.arange(n, dtype=np.int64) * SEC
    flag = np.array([True, False, None, True, None, False, True, True,
                     None], dtype=object)
    provider.add_memory_table("flags", {"flag": "b", "v": "i"}, [
        Batch(ts, {"flag": flag,
                   "v": np.arange(n, dtype=np.int64)})])
    clear_sink("results")
    prog = Planner(provider).plan(
        "SELECT v FROM flags WHERE flag = TRUE")
    LocalRunner(prog).run()
    out = Batch.concat(sink_output("results"))
    assert sorted(out.columns["v"].tolist()) == [0, 3, 6, 7]


def test_topn_fuses_into_sliding_aggregate():
    """ORDER BY agg DESC LIMIT n over a hop aggregate plans as the fused
    SlidingAggregatingTopN (optimizations.rs:293-501 analog)."""
    from arroyo_tpu.graph.logical import OpKind
    from arroyo_tpu.sql import plan_sql

    sql = """
    CREATE TABLE nexmark WITH (connector = 'nexmark', event_rate = '1000',
      num_events = '1000', rate_limited = 'false', batch_size = '256');
    SELECT bid.auction as auction,
           HOP(INTERVAL '2' SECOND, INTERVAL '10' SECOND) as window,
           count(*) AS num
    FROM nexmark WHERE bid is not null
    GROUP BY 1, 2 ORDER BY num DESC LIMIT 5
    """
    prog = plan_sql(sql)
    kinds = [n.operator.kind for n in prog.nodes()]
    assert OpKind.SLIDING_AGGREGATING_TOP_N in kinds
    assert OpKind.SLIDING_WINDOW_AGGREGATOR not in kinds
    # the global merge stage is always present, pinned to one subtask
    # (stays correct across rescales)
    topn = [n for n in prog.nodes()
            if n.operator.kind == OpKind.TUMBLING_TOP_N]
    assert len(topn) == 1
    assert topn[0].parallelism == 1 and topn[0].max_parallelism == 1
    prog.update_parallelism({topn[0].operator_id: 4})
    assert prog.node(topn[0].operator_id).parallelism == 1  # pinned


def test_exec_fused_topn_hot_items():
    """Fused sliding TopN emits the same hot items as a full aggregate
    followed by host-side ranking."""
    from arroyo_tpu.sql.schema_provider import SchemaProvider
    from arroyo_tpu.sql.planner import Planner
    from arroyo_tpu.engine.engine import LocalRunner
    from arroyo_tpu.connectors.memory import clear_sink, sink_output

    rng = np.random.default_rng(21)
    n = 3000
    ts = np.sort(rng.integers(0, 6 * SEC, n)).astype(np.int64)
    # zipf-ish hot keys
    keys = (rng.zipf(1.5, n) % 50).astype(np.int64)

    def run(sql):
        provider = SchemaProvider()
        provider.add_memory_table("events", {"k": "i"}, [
            Batch(ts, {"k": keys.copy()})])
        clear_sink("results")
        LocalRunner(Planner(provider).plan(sql)).run()
        outs = sink_output("results")
        return Batch.concat(outs) if outs else None

    fused = run("""
        SELECT k, TUMBLE(INTERVAL '2' SECOND) as window, count(*) as num
        FROM events GROUP BY 1, 2 ORDER BY num DESC LIMIT 3
    """)
    full = run("""
        SELECT k, TUMBLE(INTERVAL '2' SECOND) as window, count(*) as num
        FROM events GROUP BY 1, 2
    """)
    assert fused is not None and full is not None
    # host-side expected top3 per window from the full aggregate
    import collections
    per_window = collections.defaultdict(list)
    for i in range(len(full)):
        per_window[int(full.columns["window_end"][i])].append(
            (int(full.columns["num"][i]), int(full.columns["k"][i])))
    got = collections.defaultdict(list)
    for i in range(len(fused)):
        got[int(fused.columns["window_end"][i])].append(
            (int(fused.columns["num"][i]), int(fused.columns["k"][i])))
    assert set(got) == set(per_window)
    for w, pairs in per_window.items():
        want_counts = sorted((c for c, _ in pairs), reverse=True)[:3]
        got_counts = sorted((c for c, _ in got[w]), reverse=True)
        assert got_counts == want_counts, (w, got_counts, want_counts)


def test_exec_fused_topn_parallel_global_merge():
    """With a parallel aggregate, per-subtask local TopN prunes and the
    pinned global stage merges to exactly LIMIT rows per window."""
    from arroyo_tpu.sql.schema_provider import SchemaProvider
    from arroyo_tpu.sql.planner import Planner
    from arroyo_tpu.engine.engine import LocalRunner
    from arroyo_tpu.connectors.memory import clear_sink, sink_output
    import collections

    rng = np.random.default_rng(33)
    n = 4000
    ts = np.sort(rng.integers(0, 4 * SEC, n)).astype(np.int64)
    keys = (rng.zipf(1.4, n) % 40).astype(np.int64)
    provider = SchemaProvider()
    provider.add_memory_table("events", {"k": "i"}, [
        Batch(ts, {"k": keys})])
    clear_sink("results")
    prog = Planner(provider).plan("""
        SELECT k, TUMBLE(INTERVAL '2' SECOND) as window, count(*) as num
        FROM events GROUP BY 1, 2 ORDER BY num DESC LIMIT 3
    """, query_parallelism=2)
    LocalRunner(prog).run()
    out = Batch.concat(sink_output("results"))
    per_w = collections.Counter(int(w) for w in out.columns["window_end"])
    assert per_w and all(v <= 3 for v in per_w.values()), per_w
    # window columns survive the global merge stage intact
    np.testing.assert_array_equal(
        out.columns["window_end"] - out.columns["window_start"], 2 * SEC)
    # the true global top-3 counts per window must be what survived
    want = collections.defaultdict(collections.Counter)
    for t, k in zip(ts.tolist(), keys.tolist()):
        wend = (t // (2 * SEC) + 1) * 2 * SEC
        want[wend][k] += 1
    for wend, cnt in per_w.items():
        top = sorted(want[wend].values(), reverse=True)[:3]
        got = sorted((int(v) for w2, v in zip(
            out.columns["window_end"], out.columns["num"])
            if int(w2) == wend), reverse=True)
        assert got == top, (wend, got, top)


def test_exec_string_function_parity():
    """Scalar fn library parity additions (strings.rs/hash.rs/json.rs)."""
    p = SchemaProvider()
    ts = np.arange(3, dtype=np.int64) * 100
    p.add_memory_table("s", {"t": "s", "j": "s"}, [
        Batch(ts, {
            "t": np.array(["hello world", "Abc", "x"], dtype=object),
            "j": np.array(['{"a": {"b": 5}}', '{"a": {"b": "str"}}',
                           'nope'], dtype=object),
        })])
    out = run_sql(
        "SELECT initcap(t) as ic, left(t, 3) as l3, right(t, 2) as r2, "
        "lpad(t, 5, '*') as lp, strpos(t, 'l') as sp, ascii(t) as asc, "
        "octet_length(t) as ol, bit_length(t) as bl, "
        "translate(t, 'lo', 'LO') as tr, sha512(t) as h "
        "FROM s", p)
    assert list(out.columns["ic"]) == ["Hello World", "Abc", "X"]
    assert list(out.columns["l3"]) == ["hel", "Abc", "x"]
    assert list(out.columns["r2"]) == ["ld", "bc", "x"]
    assert list(out.columns["lp"]) == ["hello", "**Abc", "****x"]
    assert list(out.columns["sp"]) == [3, 0, 0]
    assert list(out.columns["asc"]) == [ord("h"), ord("A"), ord("x")]
    assert list(out.columns["ol"]) == [11, 3, 1]
    assert list(out.columns["bl"]) == [88, 24, 8]
    assert list(out.columns["tr"]) == ["heLLO wOrLd", "Abc", "x"]
    assert all(len(h) == 128 for h in out.columns["h"])

    # extract_json_string matches Value::String ONLY (json.rs): the
    # numeric match in row 0 is NULL, not "5"
    out = run_sql(
        "SELECT extract_json_string(j, '$.a.b') as v FROM s", p)
    assert list(out.columns["v"]) == [None, "str", None]

    # get_json_objects fans out over array nodes and returns ALL matches,
    # each JSON-encoded (json.rs returns Vec<String>); right(s, 0) is ''
    pj = SchemaProvider()
    pj.add_memory_table("js", {"j": "s", "t": "s"}, [
        Batch(np.arange(3, dtype=np.int64), {
            "j": np.array(['{"a": [{"b": 1}, {"b": 2}]}',
                           '{"a": [{"b": "x"}]}', 'nope'], dtype=object),
            "t": np.array(["hello", "ab", "z"], dtype=object),
        })])
    out = run_sql("SELECT get_json_objects(j, '$.a.b') as v, "
                  "right(t, 0) as r0 FROM js", pj)
    assert list(out.columns["v"]) == [["1", "2"], ['"x"'], None]
    assert list(out.columns["r0"]) == ["", "", ""]

    # SQL edge semantics: initcap words are alphanumeric runs; non-positive
    # pad lengths give ''; chr out of range gives null not a crash
    p2 = SchemaProvider()
    p2.add_memory_table("e", {"t": "s", "n": "i"}, [
        Batch(np.arange(2, dtype=np.int64), {
            "t": np.array(["o'neil ab1cd", "x y"], dtype=object),
            "n": np.array([65, -5], dtype=np.int64)})])
    out = run_sql("SELECT initcap(t) as ic, lpad(t, -1, '*') as lp, "
                  "chr(n) as c FROM e", p2)
    # reference semantics (strings.rs:29-41): any non-alphanumeric starts
    # a new word, digits do not -> O'Neil, Ab1cd
    assert list(out.columns["ic"]) == ["O'Neil Ab1cd", "X Y"]
    assert list(out.columns["lp"]) == ["", ""]
    assert out.columns["c"][0] == "A"


def test_exec_absolute_micros_int64_exact():
    """Absolute epoch-micros timestamps and >2^31 ids survive a jitted
    projection EXACTLY (with x64 off, JAX silently canonicalizes int64 jit
    inputs to int32 — wraparound corruption this guards against)."""
    base = 1_700_000_000_000_000  # ~2023 in epoch micros
    big = np.array([base + 1, base + 2, base + 3], dtype=np.int64)
    ids = np.array([2**40 + 7, 2**33, 5], dtype=np.int64)
    p = SchemaProvider()
    p.add_memory_table("s", {"id": "i", "dt": "t"}, [
        Batch(big, {"id": ids, "dt": big.copy()})])
    out = run_sql("SELECT id, dt, id + 1 as id1 FROM s", p)
    assert list(out.columns["id"]) == list(ids)
    assert list(out.columns["dt"]) == list(big)
    assert list(out.columns["id1"]) == [int(i) + 1 for i in ids]
    assert out.columns["id"].dtype == np.int64


def test_exec_row_number_topn_canonical_q5():
    """The canonical Nexmark q5 shape: ROW_NUMBER() OVER (PARTITION BY
    window ORDER BY num DESC) with an outer rank filter rewrites into the
    fused windowed TopN (optimizations.rs:293-501 analog)."""
    import collections

    rng = np.random.default_rng(23)
    n = 4000
    ts = np.sort(rng.integers(0, 6 * SEC, n)).astype(np.int64)
    keys = rng.integers(0, 30, n).astype(np.int64)
    p = SchemaProvider()
    p.add_memory_table("bids", {"auction": "i"}, [
        Batch(ts, {"auction": keys})])
    out = run_sql("""
        CREATE TABLE out WITH (connector='memory', name='results');
        INSERT INTO out
        SELECT auction, num, window FROM (
          SELECT B1.auction, count(*) AS num,
                 HOP(INTERVAL '2' SECOND, INTERVAL '4' SECOND) as window,
                 ROW_NUMBER() OVER (PARTITION BY window
                                    ORDER BY num DESC) as rn
          FROM bids B1 GROUP BY 1, 3
        ) WHERE rn <= 3
    """, p)
    assert out is not None and len(out) > 0
    # per window at most 3 rows, and they are the true top-3 counts
    want = collections.defaultdict(collections.Counter)
    for t, k in zip(ts.tolist(), keys.tolist()):
        e = (t // (2 * SEC) + 1) * 2 * SEC
        for w in range(2):
            want[e + w * 2 * SEC][k] += 1
    per_w = collections.defaultdict(list)
    for i in range(len(out)):
        per_w[int(out.columns["window_end"][i])].append(
            int(out.columns["num"][i]))
    assert per_w
    for wend, nums in per_w.items():
        assert len(nums) <= 3
        top = sorted(want[wend].values(), reverse=True)[:3]
        assert sorted(nums, reverse=True) == top, (wend, nums, top)


def test_row_number_without_bound_is_rank_only():
    """ROW_NUMBER() with no outer rank bound plans as rank-only TopN
    (ranks materialized, nothing pruned) — the reference's bare
    `row_number` query shape.  ASC ordering still rejects."""
    p = SchemaProvider()
    p.add_memory_table("b", {"a": "i"}, [
        Batch(np.arange(3, dtype=np.int64), {"a": np.arange(3)})])
    prog = plan_sql("""
    SELECT a FROM (
      SELECT a, count(*) as num, TUMBLE(INTERVAL '1' SECOND) as window,
             ROW_NUMBER() OVER (PARTITION BY window
                                ORDER BY num DESC) as rn
      FROM b GROUP BY 1, 3) WHERE num > 0
    """, p)
    assert not prog.validate()
    with pytest.raises(Exception, match="DESC"):
        plan_sql("""
        SELECT a FROM (
          SELECT a, count(*) as num, TUMBLE(INTERVAL '1' SECOND) as window,
                 ROW_NUMBER() OVER (PARTITION BY window
                                    ORDER BY num ASC) as rn
          FROM b GROUP BY 1, 3) WHERE rn <= 2
        """, p)


def test_exec_calendar_datetime_functions():
    """Calendar-aware date_trunc/extract (month/quarter/year/doy/week) —
    the round-1 'requires host path' gaps, verified against python
    datetime."""
    import datetime as dtm

    days = [dtm.datetime(2023, 1, 1), dtm.datetime(2023, 3, 31),
            dtm.datetime(2024, 2, 29), dtm.datetime(2024, 12, 31),
            dtm.datetime(2021, 7, 4, 13, 45, 59)]
    micros = np.array([int(d.replace(tzinfo=dtm.timezone.utc).timestamp()
                           * 1e6) for d in days], dtype=np.int64)
    p = SchemaProvider()
    p.add_memory_table("t", {"ts_col": "t"}, [
        Batch(np.arange(5, dtype=np.int64), {"ts_col": micros})])
    out = run_sql(
        "SELECT date_trunc('month', ts_col) as tm, "
        "date_trunc('quarter', ts_col) as tq, "
        "date_trunc('year', ts_col) as ty, "
        "extract('year', ts_col) as y, extract('month', ts_col) as mo, "
        "extract('day', ts_col) as d, extract('doy', ts_col) as doy, "
        "extract('quarter', ts_col) as q, extract('week', ts_col) as w "
        "FROM t", p)
    for i, d in enumerate(days):
        utc = d.replace(tzinfo=dtm.timezone.utc)
        assert int(out.columns["y"][i]) == d.year
        assert int(out.columns["mo"][i]) == d.month
        assert int(out.columns["d"][i]) == d.day
        assert int(out.columns["doy"][i]) == d.timetuple().tm_yday
        assert int(out.columns["q"][i]) == (d.month - 1) // 3 + 1
        assert int(out.columns["w"][i]) == d.isocalendar()[1]
        tm = dtm.datetime(d.year, d.month, 1, tzinfo=dtm.timezone.utc)
        assert int(out.columns["tm"][i]) == int(tm.timestamp() * 1e6)
        tq = dtm.datetime(d.year, (d.month - 1) // 3 * 3 + 1, 1,
                          tzinfo=dtm.timezone.utc)
        assert int(out.columns["tq"][i]) == int(tq.timestamp() * 1e6)
        ty = dtm.datetime(d.year, 1, 1, tzinfo=dtm.timezone.utc)
        assert int(out.columns["ty"][i]) == int(ty.timestamp() * 1e6)


def test_exec_in_subquery_semi_join():
    """x IN (SELECT ...) plans as a streaming semi-join: left rows emit
    exactly once on a match — never duplicated per right-side row."""
    p = SchemaProvider()
    lts = np.arange(6, dtype=np.int64) * 100
    p.add_memory_table("bids", {"auction": "i", "price": "i"}, [
        Batch(lts, {"auction": np.array([1, 2, 3, 4, 2, 9]),
                    "price": np.array([10, 20, 30, 40, 21, 90])})])
    # auction 2 appears TWICE on the right; auctions 5, 6 never on left
    p.add_memory_table("hot", {"a": "i"}, [
        Batch(np.arange(4, dtype=np.int64) * 100,
              {"a": np.array([2, 3, 2, 5])})])
    out = run_sql("SELECT auction, price FROM bids "
                  "WHERE auction IN (SELECT a FROM hot)", p)
    pairs = sorted(zip(out.columns["auction"].tolist(),
                       out.columns["price"].tolist()))
    assert pairs == [(2, 20), (2, 21), (3, 30)]
    assert "__sk" not in out.columns


def test_not_in_subquery_rejected():
    p = SchemaProvider()
    p.add_memory_table("t", {"a": "i"}, [
        Batch(np.arange(2, dtype=np.int64), {"a": np.arange(2)})])
    from arroyo_tpu.sql import SqlPlanError
    with pytest.raises(SqlPlanError, match="NOT IN"):
        plan_sql("SELECT a FROM t WHERE a NOT IN (SELECT a FROM t)", p)


def test_unsupported_over_rejected():
    """Any OVER clause outside the ROW_NUMBER TopN shape is an error,
    never silently planned as a plain aggregate."""
    p = events_table(SchemaProvider())
    with pytest.raises(Exception, match="OVER"):
        plan_sql("SELECT k, sum(v) OVER (PARTITION BY k) as s, "
                 "TUMBLE(INTERVAL '1' SECOND) as w FROM events "
                 "GROUP BY 1, 3", p)


def test_date_trunc_week_iso_monday():
    import datetime as dtm

    wed = dtm.datetime(2023, 1, 4, tzinfo=dtm.timezone.utc)  # Wednesday
    p = SchemaProvider()
    p.add_memory_table("t", {"ts_col": "t"}, [
        Batch(np.zeros(1, dtype=np.int64),
              {"ts_col": np.array([int(wed.timestamp() * 1e6)])})])
    out = run_sql("SELECT date_trunc('week', ts_col) as w FROM t", p)
    monday = dtm.datetime(2023, 1, 2, tzinfo=dtm.timezone.utc)
    assert int(out.columns["w"][0]) == int(monday.timestamp() * 1e6)


def test_exec_canonical_q7_highest_bid():
    """Canonical Nexmark q7: raw bids TTL-joined to the per-window MAX
    with a window-bounds filter — verified against a numpy oracle AND
    against the GROUP-BY formulation (both must agree exactly)."""
    import collections

    rng = np.random.default_rng(4)
    n = 8000
    ts = np.sort(np.random.default_rng(9).integers(
        0, 25 * SEC, n)).astype(np.int64)
    au = rng.integers(0, 50, n)
    pr = rng.integers(1, 1000, n)
    bd = rng.integers(0, 100, n)

    def provider():
        p = SchemaProvider()
        p.add_memory_table(
            "bids", {"auction": "i", "price": "i", "bidder": "i",
                     "datetime": "t"},
            [Batch(ts, {"auction": au.copy(), "price": pr.copy(),
                        "bidder": bd.copy(), "datetime": ts.copy()})])
        return p

    canonical = """
    SELECT B.auction as auction, B.price as price, B.bidder as bidder
    FROM bids B
    JOIN (
      SELECT max(price) AS maxprice, TUMBLE(INTERVAL '10' SECOND) as window
      FROM bids GROUP BY 2
    ) AS M
    ON B.price = M.maxprice
    WHERE B.datetime >= M.window_start AND B.datetime < M.window_end
    """
    out = run_sql(canonical, provider())
    got = sorted(zip(out.columns["auction"].tolist(),
                     out.columns["price"].tolist(),
                     out.columns["bidder"].tolist()))
    mx = collections.defaultdict(int)
    W = 10 * SEC
    for t, p_ in zip(ts.tolist(), pr.tolist()):
        w = (t // W + 1) * W
        mx[w] = max(mx[w], p_)
    exp = sorted((int(a), int(p_), int(b))
                 for t, a, p_, b in zip(ts.tolist(), au.tolist(),
                                        pr.tolist(), bd.tolist())
                 if p_ == mx[(t // W + 1) * W])
    assert got == exp and len(exp) > 0


# -- extended scalar function library (expressions.rs parity batch) ----------


def test_extended_math_functions():
    p = SchemaProvider()
    events_table(p)
    out = run_sql("""
      SELECT sinh(1.0) as sh, cosh(1.0) as ch, tanh(1.0) as th,
             atan2(1.0, 1.0) as a2, cbrt(27.0) as cb, cot(1.0) as ct,
             degrees(3.141592653589793) as dg, radians(180.0) as rd,
             log(100.0) as lg10, log(2.0, 8.0) as lgb, pi() as pi_,
             gcd(12, 18) as g, lcm(4, 6) as l, factorial(5) as f
      FROM events WHERE k >= 0
    """, p)
    import math
    r = {c: out.columns[c][0] for c in out.columns}
    assert abs(r["sh"] - math.sinh(1)) < 1e-4
    assert abs(r["ch"] - math.cosh(1)) < 1e-4
    assert abs(r["th"] - math.tanh(1)) < 1e-4
    assert abs(r["a2"] - math.atan2(1, 1)) < 1e-4
    assert abs(r["cb"] - 3.0) < 1e-4
    assert abs(r["ct"] - 1 / math.tan(1)) < 1e-4
    assert abs(r["dg"] - 180.0) < 1e-3
    assert abs(r["rd"] - math.pi) < 1e-4
    assert abs(r["lg10"] - 2.0) < 1e-4
    assert abs(r["lgb"] - 3.0) < 1e-4
    assert abs(r["pi_"] - math.pi) < 1e-4
    assert r["g"] == 6 and r["l"] == 12
    assert abs(r["f"] - 120.0) < 1e-3


def test_extended_string_functions():
    p = SchemaProvider()
    events_table(p)
    out = run_sql("""
      SELECT repeat(name, 2) as rep, reverse(name) as rev,
             btrim('  x  ') as bt, to_hex(255) as hx,
             encode(name, 'hex') as enc,
             decode(encode(name, 'base64'), 'base64') as rt,
             concat_ws('-', name, 'z') as cw,
             digest(name, 'sha256') as dg
      FROM events WHERE k >= 0
    """, p)
    import hashlib
    name0 = out.columns["rt"][0]  # roundtrip preserves the name
    assert out.columns["rep"][0] == name0 * 2
    assert out.columns["rev"][0] == name0[::-1]
    assert out.columns["bt"][0] == "x"
    assert out.columns["hx"][0] == "ff"
    assert out.columns["enc"][0] == name0.encode().hex()
    assert out.columns["cw"][0] == f"{name0}-z"
    assert out.columns["dg"][0] == hashlib.sha256(name0.encode()).hexdigest()


def test_uuid_random_now():
    p = SchemaProvider()
    events_table(p)
    out = run_sql("""
      SELECT uuid() as u, random() as r, now() as n, current_date as d
      FROM events WHERE k >= 0
    """, p)
    us = out.columns["u"]
    assert len(set(us.tolist())) == len(us)  # unique per row
    assert len(us[0]) == 36
    rs = out.columns["r"]
    assert ((rs >= 0) & (rs < 1)).all() and len(set(rs.tolist())) > 1
    assert out.columns["n"][0] > 1_600_000_000 * 1_000_000
    assert out.columns["d"][0] % (86_400 * 1_000_000) == 0


def test_timestamp_conversions_and_date_bin():
    p = SchemaProvider()
    events_table(p)
    out = run_sql("""
      SELECT to_timestamp_seconds(10) as s, to_timestamp_millis(10) as ms,
             to_timestamp_micros(10) as us,
             date_bin(INTERVAL '2' SECOND, v * 1000000, 0) as db
      FROM events WHERE k >= 0
    """, p)
    assert out.columns["s"][0] == 10_000_000
    assert out.columns["ms"][0] == 10_000
    assert out.columns["us"][0] == 10
    assert (out.columns["db"] % 2_000_000 == 0).all()


def test_array_functions():
    p = SchemaProvider()
    events_table(p)
    out = run_sql("""
      SELECT make_array(k, v) as arr,
             array_append(make_array(k), v) as app,
             array_contains(make_array(k, v), k) as has,
             array_length(make_array(k, v, k)) as ln,
             array_position(make_array(k, v), v) as pos,
             array_to_string(make_array(k, v), ',') as s,
             array_remove(make_array(k, v, k), k) as rm,
             trim_array(make_array(k, v), 1) as tr
      FROM events WHERE k >= 0
    """, p)
    k0 = out.columns["arr"][0][0]
    v0 = out.columns["arr"][0][1]
    assert list(out.columns["app"][0]) == [k0, v0]
    assert bool(out.columns["has"][0]) is True
    assert out.columns["ln"][0] == 3
    assert out.columns["s"][0] == f"{k0},{v0}"
    assert list(out.columns["rm"][0]) == [v0] or k0 == v0
    assert list(out.columns["tr"][0]) == [k0]


def test_gcd_lcm_factorial_exactness():
    """Reviewer-verified numeric edge cases: deep Euclid chains, int64
    lcm magnitudes, exact integer factorial, scalar-literal string fns."""
    p = SchemaProvider()
    events_table(p)
    out = run_sql("""
      SELECT gcd(1836311903, 1134903170) as g_fib,
             lcm(100000, 99999) as l_big,
             factorial(15) as f15,
             reverse('abc') as rev, repeat('ab', 3) as rep
      FROM events WHERE k >= 0
    """, p)
    assert out.columns["g_fib"][0] == 1  # consecutive Fibonacci: ~44 steps
    assert out.columns["l_big"][0] == 9_999_900_000  # > 2^31
    assert out.columns["f15"][0] == 1_307_674_368_000  # exact int64
    assert out.columns["rev"][0] == "cba"
    assert out.columns["rep"][0] == "ababab"


def test_scalar_fn_null_and_edge_semantics():
    """Advisor-flagged edge cases: factorial overflow is NULL (not a
    clamped wrong value), to_hex renders negatives as 64-bit two's
    complement, a column-valued concat_ws separator is read per row, and
    a NULL separator yields NULL (Postgres/DataFusion semantics)."""
    p = SchemaProvider()
    events_table(p)
    out = run_sql("""
      SELECT factorial(21) as fo, factorial(3) as f3,
             to_hex(-1) as h1, to_hex(-255) as h255,
             concat_ws(name, 'L', 'R') as cw,
             concat_ws(nullif('x', 'x'), 'L', 'R') as cwn
      FROM events WHERE k >= 0
    """, p)
    assert np.isnan(out.columns["fo"]).all()  # 21! overflows int64 -> NULL
    assert (out.columns["f3"] == 6).all()
    assert out.columns["h1"][0] == "ffffffffffffffff"
    assert out.columns["h255"][0] == "ffffffffffffff01"
    names = out.columns["cw"]
    assert all(s.startswith("L") and s.endswith("R") and len(s) > 2
               for s in names.tolist())  # per-row column separator
    assert all(v is None for v in out.columns["cwn"].tolist())


def test_decode_non_utf8_returns_bytes():
    """decode() of a non-UTF-8 payload must return the raw bytes, not
    replacement-character-mangled text."""
    import base64

    p = SchemaProvider()
    events_table(p)
    payload = base64.b64encode(b"\xff\xfe\x01").decode()
    out = run_sql(f"""
      SELECT decode('{payload}', 'base64') as raw,
             decode(encode(name, 'hex'), 'hex') as rt
      FROM events WHERE k >= 0
    """, p)
    assert out.columns["raw"][0] == b"\xff\xfe\x01"
    assert isinstance(out.columns["rt"][0], str)  # UTF-8 round-trips as str


def test_union_all_sql_and_stream():
    """UNION ALL — deliberate over-parity: the reference bails on unions
    (arroyo-sql/src/pipeline.rs:393)."""
    p = SchemaProvider()
    events_table(p)
    out = run_sql("""
      SELECT k, v FROM events WHERE k < 2
      UNION ALL
      SELECT k, v FROM events WHERE k >= 2
    """, p)
    # partition + union = the whole table, duplicates preserved
    whole = run_sql("SELECT k, v FROM events", p)
    assert sorted(zip(out.columns["k"].tolist(), out.columns["v"].tolist())) \
        == sorted(zip(whole.columns["k"].tolist(),
                      whole.columns["v"].tolist()))

    # three-branch chain
    out3 = run_sql("""
      SELECT k FROM events WHERE k = 0
      UNION ALL SELECT k FROM events WHERE k = 0
      UNION ALL SELECT k FROM events WHERE k = 0
    """, p)
    base = run_sql("SELECT k FROM events WHERE k = 0", p)
    assert len(out3) == 3 * len(base)

    # mismatched columns rejected
    with pytest.raises(Exception):
        run_sql("SELECT k FROM events UNION ALL SELECT v, k FROM events", p)

    # plain UNION is an explicit, honest error
    with pytest.raises(Exception):
        run_sql("SELECT k FROM events UNION SELECT k FROM events", p)


def test_union_windowed_aggregate_downstream():
    """Aggregates work over a union: watermark is the min across branches."""
    p = SchemaProvider()
    events_table(p)
    out = run_sql("""
      WITH both_halves as (
        SELECT k, v FROM events WHERE v < 25
        UNION ALL
        SELECT k, v FROM events WHERE v >= 25
      )
      SELECT k, TUMBLE(INTERVAL '2' SECOND) as window, count(*) as cnt
      FROM both_halves GROUP BY 1, 2
    """, p)
    ref = run_sql("""
      SELECT k, TUMBLE(INTERVAL '2' SECOND) as window, count(*) as cnt
      FROM events GROUP BY 1, 2
    """, p)
    got = sorted(zip(out.columns["k"].tolist(),
                     out.columns["window_start"].tolist(),
                     out.columns["cnt"].tolist()))
    want = sorted(zip(ref.columns["k"].tolist(),
                      ref.columns["window_start"].tolist(),
                      ref.columns["cnt"].tolist()))
    assert got == want and len(got) > 0


def test_create_table_format_reaches_connector():
    """format='avro' in CREATE TABLE WITH(...) must flow to the connector
    (it was silently dropped to json), and the DDL columns drive the
    synthesized Avro record schema."""
    from arroyo_tpu.connectors.kafka import InMemoryKafkaBroker
    from arroyo_tpu.formats import AvroFormat

    InMemoryKafkaBroker.reset("sqlav")
    broker = InMemoryKafkaBroker.get("sqlav")
    broker.create_topic("ev", partitions=1)
    schema = {"type": "record", "name": "ev",
              "fields": [{"name": "i", "type": ["null", "long"]},
                         {"name": "s", "type": ["null", "string"]}]}
    enc = AvroFormat(schema=schema)
    for i in range(30):
        [p] = enc.serialize([{"i": i, "s": f"r{i}"}])
        broker.produce("ev", p, partition=0)

    out = run_sql("""
      CREATE TABLE ev (i bigint, s text) WITH (
        connector = 'kafka', bootstrap_servers = 'memory://sqlav',
        topic = 'ev', format = 'avro', max_messages = '30');
      SELECT i, s FROM ev
    """)
    assert sorted(out.columns["i"].tolist()) == list(range(30))
    assert out.columns["s"][0].startswith("r")


def test_union_reviewer_edge_cases():
    """CTE visibility in union branches, self-union duplication, trailing
    ORDER BY rejection, type compatibility (reviewer-found)."""
    p = SchemaProvider()
    events_table(p)

    # CTE visible in the second branch
    out = run_sql("""
      WITH x AS (SELECT k, v FROM events)
      SELECT k, v FROM x WHERE k < 2
      UNION ALL
      SELECT k, v FROM x WHERE k >= 2
    """, p)
    whole = run_sql("SELECT k, v FROM events", p)
    assert len(out) == len(whole)

    # self-union through the fluent API duplicates rows
    from arroyo_tpu import Batch, Stream
    from arroyo_tpu.connectors.memory import clear_sink, sink_output
    from arroyo_tpu.engine.engine import LocalRunner
    import numpy as np

    clear_sink("su")
    src = Batch(np.arange(5, dtype=np.int64),
                {"v": np.arange(5, dtype=np.int64)})
    s = (Stream.source("memory", {"batches": [src]})
         .map(lambda c: {"v": c["v"]}, name="id"))
    prog = s.union(s).sink("memory", {"name": "su"})
    LocalRunner(prog).run()
    got = sorted(r for b in sink_output("su") for r in b.columns["v"].tolist())
    assert got == sorted(list(range(5)) * 2)  # every row twice

    # trailing ORDER BY/LIMIT is rejected with guidance
    with pytest.raises(Exception, match="outer SELECT"):
        run_sql("""SELECT k FROM events UNION ALL
                   SELECT k FROM events ORDER BY k LIMIT 3""", p)

    # same names, different types -> rejected
    with pytest.raises(Exception, match="columns and"):
        run_sql("""SELECT k, name FROM events UNION ALL
                   SELECT k, v as name FROM events""", p)


def test_union_leading_order_by_rejected():
    p = SchemaProvider()
    events_table(p)
    with pytest.raises(Exception, match="subquery"):
        run_sql("""SELECT k FROM events ORDER BY k LIMIT 3
                   UNION ALL SELECT k FROM events""", p)


def test_json_path_indexers():
    """jsonpath array indexers: [n] and [*] segments (json.rs parity)."""
    import numpy as np

    from arroyo_tpu.sql.functions import HOST_FUNCTIONS

    gj = HOST_FUNCTIONS["get_json_objects"]
    v = np.array(['{"a": [{"b": 1}, {"b": 2}], "c": [10, 20]}'], dtype=object)
    assert list(gj([(v, None), ("$.a[*].b", None)])[0][0]) == ["1", "2"]
    assert list(gj([(v, None), ("$.a[0].b", None)])[0][0]) == ["1"]
    assert list(gj([(v, None), ("$.a[1].b", None)])[0][0]) == ["2"]
    assert list(gj([(v, None), ("$.c[1]", None)])[0][0]) == ["20"]
    assert list(gj([(v, None), ("$.a[5].b", None)])[0][0]) == []

    first = HOST_FUNCTIONS["get_first_json_object"]
    out, _ = first([(v, None), ("$.a[1]", None)])
    assert "2" in out[0]


def test_json_path_indexer_edge_cases():
    """Reviewer-reproduced: bad bracket forms yield no matches (never a
    crash), '$'-containing keys survive, [n] never indexes strings."""
    import numpy as np

    from arroyo_tpu.sql.functions import HOST_FUNCTIONS

    gj = HOST_FUNCTIONS["get_json_objects"]
    ref = np.array(['{"a": {"$ref": 7}}'], dtype=object)
    assert list(gj([(ref, None), ("$.a.$ref", None)])[0][0]) == ["7"]

    s = np.array(['{"c": "hello"}'], dtype=object)
    assert list(gj([(s, None), ("$.c[0]", None)])[0][0]) == []

    for bad in ("$['c']", "$.a[1:3]", "$.a[]"):
        out, _ = gj([(s, None), (bad, None)])
        assert list(out[0]) == []  # no match, no exception


def test_explain_emits_plan_rows():
    """EXPLAIN SELECT ... runs as a pipeline emitting the planned operator
    DAG as rows (the reference bails on EXPLAIN, pipeline.rs:432)."""
    p = SchemaProvider()
    events_table(p)
    out = run_sql("""
      EXPLAIN SELECT k, TUMBLE(INTERVAL '2' SECOND) as window,
                     count(*) as cnt
      FROM events GROUP BY 1, 2
    """, p)
    ops = list(out.columns["operator"])
    assert "connector_source" in ops
    assert any("aggregator" in o or "window" in o for o in ops)
    assert out.columns["parallelism"].dtype.kind == "i"
    # inputs column wires the DAG
    assert any(out.columns["inputs"][i] for i in range(len(out)))


def test_explain_rejects_mixed_statements():
    p = SchemaProvider()
    events_table(p)
    with pytest.raises(Exception, match="only executable"):
        run_sql("SELECT k FROM events; EXPLAIN SELECT k FROM events", p)


def test_common_subplan_elimination_q5_shape(monkeypatch):
    """Textually duplicated subqueries (nexmark q5's AuctionBids vs
    CountBids — same hop aggregate behind different table aliases) merge
    into ONE aggregate chain; output is identical with the pass off.
    Reference comparison: DataFusion does not dedupe across join inputs,
    so the reference runs the chain twice (double state, double fires)."""
    import os

    # pin the CSE-specific plan shape: the argmax fusion would rewrite
    # this self-join wholesale (it has its own tests)
    monkeypatch.setenv("ARROYO_ARGMAX", "0")

    sql = """
    CREATE TABLE nexmark WITH (
      connector = 'nexmark', event_rate = '1000000',
      num_events = '60000', rate_limited = 'false', batch_size = '8192',
      base_time_micros = '1700000000000000'
    );
    WITH bids as (SELECT bid.auction as auction, bid.datetime as datetime
        FROM nexmark where bid is not null)
    SELECT AuctionBids.auction as auction, AuctionBids.num as num
    FROM (
      SELECT B1.auction, HOP(INTERVAL '2' SECOND, INTERVAL '10' SECOND)
             as window, count(*) AS num
      FROM bids B1 GROUP BY 1, 2
    ) AS AuctionBids
    JOIN (
      SELECT max(num) AS maxn, window
      FROM (
        SELECT count(*) AS num,
               HOP(INTERVAL '2' SECOND, INTERVAL '10' SECOND) AS window
        FROM bids B2 GROUP BY B2.auction, 2
      ) AS CountBids
      GROUP BY 2
    ) AS MaxBids
    ON AuctionBids.num = MaxBids.maxn
       and AuctionBids.window = MaxBids.window
    """
    prog = plan_sql(sql)
    aggs = [n for n in prog.graph.nodes if "sliding_window" in n]
    assert len(aggs) == 1, f"duplicated hop aggregate not merged: {aggs}"

    def run():
        clear_sink("results")
        LocalRunner(plan_sql(sql)).run()
        rows = []
        for b in sink_output("results"):
            for i in range(len(next(iter(b.columns.values())))):
                rows.append(tuple(int(b.columns[c][i])
                                  for c in sorted(b.columns)))
        return sorted(rows)

    merged = run()
    os.environ["ARROYO_CSE"] = "0"
    try:
        dup_prog = plan_sql(sql)
        assert len([n for n in dup_prog.graph.nodes
                    if "sliding_window" in n]) == 2
        unmerged = run()
    finally:
        os.environ.pop("ARROYO_CSE", None)
    assert merged == unmerged and len(merged) > 0


def test_replayable_source_scans_merge():
    """Two scans of the same deterministic table (q8 reads nexmark for
    persons AND auctions) merge into one generation pass with the union
    of the pushed-down projections; results are unchanged.  Consumption-
    stateful connectors (kafka) must never merge."""
    import os

    sql = """
    CREATE TABLE nexmark WITH (
      connector = 'nexmark', event_rate = '1000000',
      num_events = '40000', rate_limited = 'false', batch_size = '8192',
      base_time_micros = '1700000000000000'
    );
    SELECT P.id as id, P.np as np, A.na as na
    FROM (
      SELECT person.id as id, TUMBLE(INTERVAL '10' SECOND) as window,
             count(*) as np
      FROM nexmark WHERE person is not null GROUP BY 1, 2
    ) AS P
    JOIN (
      SELECT auction.seller as seller, TUMBLE(INTERVAL '10' SECOND)
             as window, count(*) as na
      FROM nexmark WHERE auction is not null GROUP BY 1, 2
    ) AS A
    ON P.id = A.seller and P.window = A.window
    """
    prog = plan_sql(sql)
    srcs = [n for n in prog.graph.nodes if "connector_source" in n]
    assert len(srcs) == 1, f"q8's two nexmark scans did not merge: {srcs}"
    proj = prog.graph.nodes[srcs[0]]["node"].operator.spec.config[
        "projection"]
    assert "person_id" in proj and "auction_seller" in proj  # union

    def run():
        clear_sink("results")
        LocalRunner(plan_sql(sql)).run()
        rows = []
        for b in sink_output("results"):
            for i in range(len(next(iter(b.columns.values())))):
                rows.append(tuple(int(b.columns[c][i])
                                  for c in sorted(b.columns)))
        return sorted(rows)

    merged = run()
    os.environ["ARROYO_CSE"] = "0"
    try:
        unmerged = run()
    finally:
        os.environ.pop("ARROYO_CSE", None)
    assert merged == unmerged and len(merged) > 0

    # kafka scans must NOT merge (consumer/offset state)
    ksql = """
    CREATE TABLE t (v BIGINT) WITH (
      connector = 'kafka', topic = 'x',
      bootstrap_servers = 'memory://srcmerge', format = 'json',
      max_messages = '1'
    );
    SELECT a.v FROM (SELECT v FROM t) a JOIN (SELECT v FROM t) b ON a.v = b.v
    """
    kprog = plan_sql(ksql)
    ksrcs = [n for n in kprog.graph.nodes if "connector_source" in n]
    assert len(ksrcs) == 2, "kafka sources must not merge"


def test_argmax_fusion_bails_on_non_matching_shapes():
    """The argmax rewrite must prove the self-join's two sides identical;
    near-misses (different window widths, different inner aggregates,
    outer joins, HAVING) keep the full join plan."""
    def plan(sql):
        from arroyo_tpu.sql.planner import Planner

        provider = SchemaProvider()
        provider.add_memory_table("events", {"k": "i", "v": "i"}, [
            Batch(np.array([0], dtype=np.int64),
                  {"k": np.array([1], dtype=np.int64),
                   "v": np.array([1], dtype=np.int64)})])
        return Planner(provider).plan(sql)

    def shape(prog):
        return (sum(1 for n in prog.graph.nodes if "window_join" in n),
                sum(1 for n in prog.graph.nodes if "window_argmax" in n))

    tpl = """
    WITH ev AS (SELECT k AS k, v AS v FROM events)
    SELECT A.k AS k, A.num AS num
    FROM (
      SELECT T1.k, TUMBLE(INTERVAL '{wl}' SECOND) AS window,
             {aggl} AS num FROM ev T1 GROUP BY 1, 2
    ) AS A
    {kind} JOIN (
      SELECT max(num) AS mx, window FROM (
        SELECT {aggr} AS num, TUMBLE(INTERVAL '{wr}' SECOND) AS window
        FROM ev T2 GROUP BY T2.k, 2
      ) AS B0 GROUP BY 2
    ) AS B
    ON A.num = B.mx AND A.window = B.window
    """
    # identical sides: fuses
    assert shape(plan(tpl.format(wl=2, wr=2, aggl="count(*)",
                                 aggr="count(*)", kind=""))) == (0, 1)
    # different window widths: window refs differ -> full join
    assert shape(plan(tpl.format(wl=2, wr=4, aggl="count(*)",
                                 aggr="count(*)", kind="")))[1] == 0
    # different inner aggregates: subplans differ -> full join
    assert shape(plan(tpl.format(wl=2, wr=2, aggl="count(*)",
                                 aggr="sum(v)", kind=""))) == (1, 0)
    # outer join kind: never fused
    assert shape(plan(tpl.format(wl=2, wr=2, aggl="count(*)",
                                 aggr="count(*)", kind="LEFT"))) == (1, 0)


def test_argmax_fusion_bails_on_per_key_max():
    """GROUP BY window, k on the max side is a PER-KEY max — fusing it
    to a global per-window argmax would silently change results
    (code-review r4 finding, verified repro): must keep the full join."""
    from arroyo_tpu.sql.planner import Planner

    provider = SchemaProvider()
    provider.add_memory_table("events", {"k": "i", "v": "i"}, [
        Batch(np.array([0], dtype=np.int64),
              {"k": np.array([1], dtype=np.int64),
               "v": np.array([1], dtype=np.int64)})])
    prog = Planner(provider).plan("""
    WITH ev AS (SELECT k AS k, v AS v FROM events)
    SELECT A.k AS k, A.num AS num
    FROM (
      SELECT T1.k, TUMBLE(INTERVAL '2' SECOND) AS window,
             count(*) AS num FROM ev T1 GROUP BY 1, 2
    ) AS A
    JOIN (
      SELECT max(num) AS mx, window FROM (
        SELECT count(*) AS num, k AS k,
               TUMBLE(INTERVAL '2' SECOND) AS window
        FROM ev T2 GROUP BY 2, 3
      ) AS B0 GROUP BY window, k
    ) AS B
    ON A.num = B.mx AND A.window = B.window
    """)
    assert not any("window_argmax" in n for n in prog.graph.nodes)
    assert any("join" in n for n in prog.graph.nodes)


# -- raw-stream argmax fusion (q7's shape; event-time provenance) -----------


RAW_ARGMAX_TPL = """
WITH bids as (SELECT bid.auction as auction, bid.price as price,
                     bid.bidder as bidder, bid.datetime as datetime
    FROM nexmark where bid is not null)
SELECT B.auction as auction, B.price as price, B.bidder as bidder
FROM bids B
JOIN (
  SELECT max({val}) AS maxprice, {win} as window
  FROM bids GROUP BY 2{extra_group}
) AS M
ON B.{joincol} = M.maxprice
WHERE {lower} AND {upper}
"""


def _plan_raw_argmax(val="price", win="TUMBLE(INTERVAL '10' SECOND)",
                     joincol="price",
                     lower="B.datetime >= M.window_start",
                     upper="B.datetime < M.window_end", extra_group=""):
    sql = ("CREATE TABLE nexmark WITH (connector = 'nexmark', "
           "event_rate = '1000', num_events = '100', "
           "rate_limited = 'false');"
           + RAW_ARGMAX_TPL.format(val=val, win=win, joincol=joincol,
                                   lower=lower, upper=upper,
                                   extra_group=extra_group))
    return plan_sql(sql)


def _shape(prog):
    return (sum(1 for n in prog.graph.nodes if "join" in n),
            sum(1 for n in prog.graph.nodes if "window_argmax" in n))


def test_raw_argmax_fusion_q7_plans_without_join():
    """q7's raw-stream self-join on a tumbling window max fuses to one
    WindowArgmax operator: the whole max-side aggregate chain and the
    TTL join disappear (planner._try_raw_argmax_fusion; the reference
    runs the full join — optimizations.rs has no analogous rewrite)."""
    assert _shape(_plan_raw_argmax()) == (0, 1)
    # flipped conjunct orientation proves the same bounds
    assert _shape(_plan_raw_argmax(
        lower="M.window_start <= B.datetime",
        upper="M.window_end > B.datetime")) == (0, 1)
    # strict lower bound still pins rows to their own window
    assert _shape(_plan_raw_argmax(
        lower="B.datetime > M.window_start")) == (0, 1)


def test_raw_argmax_fusion_negative_shapes():
    """Every unprovable variant must keep the full join plan (a missed
    optimization, never a wrong plan)."""
    # sliding window: each row is in width/slide windows
    assert _shape(_plan_raw_argmax(
        win="HOP(INTERVAL '2' SECOND, INTERVAL '10' SECOND)"))[1] == 0
    # non-strict upper bound admits the previous window's boundary row
    assert _shape(_plan_raw_argmax(
        upper="B.datetime <= M.window_end"))[1] == 0
    # missing a bound: the join is not pinned to one window
    assert _shape(_plan_raw_argmax(upper="B.price > 0"))[1] == 0
    assert _shape(_plan_raw_argmax(lower="B.price > 0"))[1] == 0
    # WHERE column without event-time provenance (price != __timestamp)
    assert _shape(_plan_raw_argmax(
        lower="B.price >= M.window_start",
        upper="B.price < M.window_end"))[1] == 0
    # join column differs from the maximized column
    assert _shape(_plan_raw_argmax(joincol="bidder"))[1] == 0
    # per-key max on the right side is not a global window extremum
    assert _shape(_plan_raw_argmax(extra_group=", auction"))[1] == 0


def test_raw_argmax_fusion_memory_table_oracle():
    """Fused raw argmax over a memory table WITH event_time_field: exact
    row-set equality against the unfused TTL-join plan and a numpy
    oracle, including max ties (all tying rows emit, as the join emits
    them).  The same table WITHOUT event_time_field has no provenance
    and must keep the join plan."""
    import collections
    import os

    rng = np.random.default_rng(11)
    n = 4000
    ts = np.sort(rng.integers(0, 25 * SEC, n)).astype(np.int64)
    au = rng.integers(0, 40, n)
    pr = rng.integers(1, 60, n)  # small range -> many exact ties

    def provider(et):
        p = SchemaProvider()
        p.add_memory_table(
            "rawbids", {"auction": "i", "price": "i", "datetime": "t"},
            [Batch(ts, {"auction": au.copy(), "price": pr.copy(),
                        "datetime": ts.copy()})],
            event_time_field=et)
        return p

    sql = """
    SELECT B.auction as auction, B.price as price
    FROM rawbids B
    JOIN (
      SELECT max(price) AS mx, TUMBLE(INTERVAL '10' SECOND) as window
      FROM rawbids GROUP BY 2
    ) AS M
    ON B.price = M.mx
    WHERE B.datetime >= M.window_start AND B.datetime < M.window_end
    """
    prog = plan_sql(sql, provider("datetime"))
    assert _shape(prog) == (0, 1)
    assert _shape(plan_sql(sql, provider(None)))[1] == 0

    def rows(fused):
        os.environ["ARROYO_ARGMAX"] = "1" if fused else "0"
        try:
            out = run_sql(sql, provider("datetime"))
        finally:
            os.environ.pop("ARROYO_ARGMAX", None)
        return sorted(zip(out.columns["auction"].tolist(),
                          out.columns["price"].tolist()))

    W = 10 * SEC
    mx = collections.defaultdict(int)
    for t, p_ in zip(ts.tolist(), pr.tolist()):
        mx[t // W] = max(mx[t // W], p_)
    exp = sorted((int(a), int(p_))
                 for t, a, p_ in zip(ts.tolist(), au.tolist(), pr.tolist())
                 if p_ == mx[t // W])
    got = rows(True)
    assert got == rows(False) == exp
    assert len(exp) > len(mx), "tie coverage: more rows than windows"


def test_raw_argmax_union_branch_drops_provenance():
    """UNION ALL keeps event-time provenance only where EVERY branch
    proves it: a branch aliasing a non-event-time column onto the et
    name would be mis-windowed by the fusion (code-review r5 finding,
    verified repro) — the plan must keep the join."""
    ts = np.array([1 * SEC, 12 * SEC], dtype=np.int64)
    other = np.array([15 * SEC, 3 * SEC], dtype=np.int64)
    v = np.array([7, 7], dtype=np.int64)

    def provider():
        p = SchemaProvider()
        p.add_memory_table(
            "t1", {"et": "t", "other": "t", "v": "i"},
            [Batch(ts, {"et": ts.copy(), "other": other.copy(),
                        "v": v.copy()})],
            event_time_field="et")
        return p

    sql = """
    WITH u AS (SELECT et AS et, v AS v FROM t1
               UNION ALL SELECT other AS et, v AS v FROM t1)
    SELECT B.v AS v, B.et AS et
    FROM u B
    JOIN (
      SELECT max(v) AS mx, TUMBLE(INTERVAL '10' SECOND) AS window
      FROM u GROUP BY 2
    ) AS M
    ON B.v = M.mx
    WHERE B.et >= M.window_start AND B.et < M.window_end
    """
    prog = plan_sql(sql, provider())
    assert not any("window_argmax" in n for n in prog.graph.nodes)
    out = run_sql(sql, provider())
    got = sorted(zip(out.columns["v"].tolist(),
                     (np.asarray(out.columns["et"]) // SEC).tolist()))
    # all four rows match: each branch-2 row's et lands in a window
    # whose max (7) it equals
    assert got == [(7, 1), (7, 3), (7, 12), (7, 15)]


def test_raw_argmax_late_rows_match_final_extremum():
    """A genuinely-late row (beyond the watermark) for a released window
    must behave exactly as in the TTL join the fusion replaces: the max
    row is still in TTL state, so a late tying probe emits and a late
    non-tying probe doesn't (code-review r5 finding, verified repro)."""
    import os

    b1 = Batch(np.array([1 * SEC, 12 * SEC], dtype=np.int64),
               {"a": np.array([1, 2], dtype=np.int64),
                "v": np.array([9.0, 3.0]),
                "et": np.array([1 * SEC, 12 * SEC], dtype=np.int64)})
    # late rows for window [0, 10s): one ties the final max 9.0, one not
    b2 = Batch(np.array([5 * SEC, 6 * SEC, 13 * SEC], dtype=np.int64),
               {"a": np.array([3, 5, 4], dtype=np.int64),
                "v": np.array([9.0, 8.0, 3.0]),
                "et": np.array([5 * SEC, 6 * SEC, 13 * SEC],
                               dtype=np.int64)})

    def provider():
        p = SchemaProvider()
        p.add_memory_table("lb", {"a": "i", "v": "f", "et": "t"},
                           [b1, b2], event_time_field="et")
        return p

    sql = """
    SELECT B.a AS a, B.v AS v
    FROM lb B
    JOIN (
      SELECT max(v) AS mx, TUMBLE(INTERVAL '10' SECOND) AS window
      FROM lb GROUP BY 2
    ) AS M
    ON B.v = M.mx
    WHERE B.et >= M.window_start AND B.et < M.window_end
    """

    def rows(fused):
        os.environ["ARROYO_ARGMAX"] = "1" if fused else "0"
        try:
            out = run_sql(sql, provider())
        finally:
            os.environ.pop("ARROYO_ARGMAX", None)
        return sorted(zip(out.columns["a"].tolist(),
                          out.columns["v"].tolist()))

    fused, unfused = rows(True), rows(False)
    assert fused == unfused
    assert (3, 9.0) in fused and (5, 8.0) not in fused


def test_null_join_keys_never_match():
    """SQL NULL join keys match nothing — not even each other (the
    reference's hash join skips null keys).  Null-keyed rows still
    emit null-padded on their outer side.  Pre-fix, two NaN keys
    hashed equal and joined."""
    from collections import Counter

    from arroyo_tpu.sql.planner import Planner
    from arroyo_tpu.types import UPDATE_OP_COLUMN, UpdateOp

    def run(kind):
        provider = SchemaProvider()
        ts = np.array([0, 1000, 2000], dtype=np.int64)
        provider.add_memory_table("l", {"a": "f", "x": "i"}, [
            Batch(ts, {"a": np.array([1.0, np.nan, 3.0]),
                       "x": np.array([10, 11, 12], np.int64)})])
        provider.add_memory_table("r", {"a": "f", "y": "i"}, [
            Batch(ts, {"a": np.array([np.nan, 3.0, 4.0]),
                       "y": np.array([20, 21, 22], np.int64)})])
        clear_sink("results")
        LocalRunner(Planner(provider).plan(
            f"SELECT l.x AS x, r.y AS y FROM l {kind} JOIN r "
            "ON l.a = r.a")).run()
        net = Counter()
        for b in sink_output("results"):
            n = len(next(iter(b.columns.values())))
            ops = (np.asarray(b.columns[UPDATE_OP_COLUMN])
                   if UPDATE_OP_COLUMN in b.columns
                   else np.zeros(n, np.int8))
            for i in range(n):
                fmt = lambda v: (None if v is None
                                 or (isinstance(v, float) and np.isnan(v))
                                 else int(v))
                row = (fmt(b.columns["x"][i]), fmt(b.columns["y"][i]))
                net[row] += (-1 if ops[i] == UpdateOp.DELETE.value else 1)
        return sorted((r for r, c in net.items() for _ in range(c)),
                      key=repr)

    assert run("") == [(12, 21)]
    assert run("LEFT") == [(10, None), (11, None), (12, 21)]
    assert run("RIGHT") == [(12, 21), (None, 20), (None, 22)]
    assert run("FULL") == [(10, None), (11, None), (12, 21),
                           (None, 20), (None, 22)]


def test_count_distinct_excludes_nulls():
    """COUNT(DISTINCT x) must not count NULLs — and NaN != NaN made
    every null row its own 'distinct' value (returned 5, not 3)."""
    from arroyo_tpu.sql.planner import Planner

    provider = SchemaProvider()
    ts = np.arange(6, dtype=np.int64) * 1000
    provider.add_memory_table("t", {"k": "i", "v": "f"}, [
        Batch(ts, {"k": np.zeros(6, np.int64),
                   "v": np.array([1.0, 2.0, np.nan, 2.0, np.nan, 3.0])})])
    clear_sink("results")
    LocalRunner(Planner(provider).plan("""
    SELECT k, TUMBLE(INTERVAL '1' SECOND) AS window,
           count(DISTINCT v) AS d, count(v) AS c, count(*) AS s
    FROM t GROUP BY 1, 2""")).run()
    b = Batch.concat(sink_output("results"))
    assert int(b.columns["d"][0]) == 3
    assert int(b.columns["c"][0]) == 4
    assert int(b.columns["s"][0]) == 6


def test_in_subquery_null_never_matches():
    """`x IN (SELECT ...)` is never TRUE for NULL x, and a NULL in the
    subquery matches nothing (same NaN-hash defect class as the join
    fix; semi joins route through the same nonce mechanism)."""
    from arroyo_tpu.sql.planner import Planner

    provider = SchemaProvider()
    ts = np.arange(3, dtype=np.int64) * 1000
    provider.add_memory_table("l", {"a": "f", "x": "i"}, [
        Batch(ts, {"a": np.array([1.0, np.nan, 3.0]),
                   "x": np.array([10, 11, 12], np.int64)})])
    provider.add_memory_table("r", {"b": "f"}, [
        Batch(ts, {"b": np.array([np.nan, 3.0, 4.0])})])
    clear_sink("results")
    LocalRunner(Planner(provider).plan(
        "SELECT x FROM l WHERE a IN (SELECT b FROM r)")).run()
    got = sorted(int(v) for b in sink_output("results")
                 for v in b.columns["x"])
    assert got == [12], got  # NaN 'in' {NaN, ...} must NOT match


def test_sql_division_modulo_semantics():
    """SQL integer division TRUNCATES toward zero, % carries the
    dividend's sign, and both are NULL on a zero divisor.  Pre-fix,
    the jnp.maximum(rv, 1) guard silently clamped EVERY divisor below
    one: 10/0 returned 10 and 10/-2 returned 10."""
    from arroyo_tpu.sql.planner import Planner

    provider = SchemaProvider()
    ts = np.arange(6, dtype=np.int64) * 1000
    provider.add_memory_table("t", {"a": "i", "b": "i"}, [
        Batch(ts, {"a": np.array([10, 10, -7, -7, 10, 7], np.int64),
                   "b": np.array([4, 0, 2, -2, -2, 2], np.int64)})])
    clear_sink("results")
    LocalRunner(Planner(provider).plan(
        "SELECT a / b AS q, a % b AS r FROM t")).run()
    rows = []
    for batch in sink_output("results"):
        for i in range(len(batch.columns["q"])):
            fmt = lambda v: (None if isinstance(v, float) and np.isnan(v)
                             else int(v))
            rows.append((fmt(batch.columns["q"][i]),
                         fmt(batch.columns["r"][i])))
    assert rows == [(2, 2), (None, None), (-3, -1), (3, -1), (-5, 0),
                    (3, 1)], rows


def test_string_min_max_aggregates():
    """MIN/MAX over strings (lexicographic, NULLs skipped) run on the
    buffered window path's host reduce; SUM/AVG over strings are plan-
    time type errors.  Pre-fix, MIN(string) crashed the worker task
    mid-stream with a float-coercion error."""
    from arroyo_tpu.sql.planner import Planner

    provider = SchemaProvider()
    ts = np.arange(4, dtype=np.int64) * 1000
    provider.add_memory_table("t", {"s": "s", "v": "i"}, [
        Batch(ts, {"s": np.array(["b", "a", None, "c"], dtype=object),
                   "v": np.array([4, 0, 2, 1], np.int64)})])
    clear_sink("results")
    LocalRunner(Planner(provider).plan("""
    SELECT TUMBLE(INTERVAL '1' SECOND) AS window,
           min(s) AS lo, max(s) AS hi, count(*) AS c
    FROM t GROUP BY 1""")).run()
    b = Batch.concat(sink_output("results"))
    assert b.columns["lo"][0] == "a"
    assert b.columns["hi"][0] == "c"
    assert int(b.columns["c"][0]) == 4
    from arroyo_tpu.sql import SqlPlanError

    with pytest.raises(SqlPlanError, match="not defined for string"):
        Planner(provider).plan(
            "SELECT TUMBLE(INTERVAL '1' SECOND) AS w, sum(s) AS x "
            "FROM t GROUP BY 1")


def test_string_min_max_non_windowed():
    """Non-windowed GROUP BY string MIN/MAX merges refinements across
    batches, including an all-NULL first segment (pre-fix: min('b',
    None) raised TypeError mid-stream)."""
    from arroyo_tpu.sql.planner import Planner

    provider = SchemaProvider()
    provider.add_memory_table("t", {"k": "i", "s": "s"}, [
        Batch(np.array([0], np.int64),
              {"k": np.array([1], np.int64),
               "s": np.array([None], dtype=object)}),
        Batch(np.array([1000], np.int64),
              {"k": np.array([1], np.int64),
               "s": np.array(["b"], dtype=object)})])
    clear_sink("results")
    LocalRunner(Planner(provider).plan(
        "SELECT k, min(s) AS lo FROM t GROUP BY k")).run()
    vals = [b.columns["lo"][i] for b in sink_output("results")
            for i in range(len(b.columns["lo"]))]
    assert vals[-1] == "b", vals  # final refinement carries the value


def test_string_null_semantics_in_expressions():
    """String NULLs carry validity through expressions: NULL = NULL is
    never TRUE (WHERE s = s filters NULL rows), NULL LIKE and
    upper(NULL) are NULL, and CAST of a NULL float is NULL, not 0."""
    from arroyo_tpu.sql.planner import Planner

    provider = SchemaProvider()
    ts = np.arange(3, dtype=np.int64) * 1000
    provider.add_memory_table("t", {"v": "f", "s": "s"}, [
        Batch(ts, {"v": np.array([1.5, np.nan, -2.5]),
                   "s": np.array(["abc", None, "xbc"], dtype=object)})])

    def run(sql):
        clear_sink("results")
        LocalRunner(Planner(provider).plan(sql)).run()
        out = []
        for b in sink_output("results"):
            for i in range(len(next(iter(b.columns.values())))):
                x = next(iter(b.columns.values()))[i]
                out.append(None if x is None
                           or (isinstance(x, float) and np.isnan(x))
                           else x)
        return out

    assert len(run("SELECT v FROM t WHERE s = s")) == 2  # NULL row drops
    assert run("SELECT s LIKE 'a%' AS a FROM t") == [True, None, False]
    assert run("SELECT upper(s) AS u FROM t") == ["ABC", None, "XBC"]
    assert len(run("SELECT s FROM t WHERE s IS NULL")) == 1
    got = run("SELECT CAST(v AS BIGINT) AS a FROM t")
    assert [None if g is None else int(g) for g in got] == [1, None, -2]


def test_extract_from_form_and_constant_predicates():
    """Standard SQL EXTRACT(field FROM expr) parses (normalizing to the
    two-arg form), and constant WHERE predicates (now()-only
    comparisons) broadcast their scalar mask instead of dimension-
    lifting every column to (1, n) and crashing the next operator."""
    from arroyo_tpu.sql.planner import Planner

    provider = SchemaProvider()
    base = 1_700_000_000_000_000
    ts = np.array([base, base + 2_500_000], dtype=np.int64)
    provider.add_memory_table("t", {"k": "i"}, [
        Batch(ts, {"k": np.array([1, 2], np.int64)})])

    clear_sink("results")
    LocalRunner(Planner(provider).plan("""
    SELECT extract(minute FROM window_end) AS m, count(*) AS c
    FROM t GROUP BY TUMBLE(INTERVAL '1' MINUTE)""")).run()
    b = Batch.concat(sink_output("results"))
    assert len(b) >= 1 and int(b.columns["c"].sum()) == 2

    for sql, exp in [
        ("SELECT k FROM t WHERE date_trunc('minute', now()) > "
         "now() - INTERVAL '1' HOUR", 2),
        ("SELECT k FROM t WHERE now() < now() - INTERVAL '1' HOUR", 0),
    ]:
        clear_sink("results")
        LocalRunner(Planner(provider).plan(sql)).run()
        got = sum(len(bb.columns.get("k", []))
                  for bb in sink_output("results"))
        assert got == exp, (sql, got)


def test_json_sink_int64_and_null_fidelity(tmp_path):
    """BIGINTs above 2^53 survive the JSON sink exactly (a float round-
    trip would corrupt them) and NULL strings serialize as JSON null."""
    import json as _json

    from arroyo_tpu.sql.planner import Planner

    provider = SchemaProvider()
    big = 2 ** 62 + 12345
    ts = np.array([0, 1000], dtype=np.int64)
    provider.add_memory_table("t", {"k": "i", "s": "s"}, [
        Batch(ts, {"k": np.array([big, 7], np.int64),
                   "s": np.array(["x", None], dtype=object)})])
    out = str(tmp_path / "out.jsonl")
    LocalRunner(Planner(provider).plan(f"""
    CREATE TABLE sinkt (k BIGINT, s TEXT) WITH (
      connector = 'single_file', path = '{out}', type = 'sink');
    INSERT INTO sinkt SELECT k, s FROM t""")).run()
    rows = [_json.loads(line) for line in open(out)]
    assert rows[0]["k"] == big
    assert rows[1]["s"] is None
