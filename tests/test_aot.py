"""AOT compile stage (engine/aot.py) — the reference compiler.rs analog."""

import numpy as np
import pytest

from arroyo_tpu import Stream
from arroyo_tpu.engine.aot import (
    CompileReport,
    compile_program,
    deserialize_step,
    enable_persistent_cache,
    load_step,
    serialize_step,
    store_step,
)


def test_compile_program_ok():
    prog = (Stream.source("impulse", {"event_rate": 0.0, "message_count": 10})
            .map(lambda c: {"x": c["counter"] * 2}, name="m")
            .sink("blackhole", {}))
    report = compile_program(prog)
    assert report.ok and len(report.operators) == 3
    assert "ImpulseSource" in report.operators.values()


def test_compile_program_construction_error_fails_early():
    from arroyo_tpu.connectors.registry import (
        ConnectorMeta,
        register_connector,
    )

    class BrokenSink:
        def __init__(self, cfg):
            raise RuntimeError("cannot reach upstream service")

    register_connector(ConnectorMeta(
        name="_aot_broken", description="test", sink_factory=BrokenSink))
    prog = (Stream.source("impulse", {"event_rate": 0.0, "message_count": 10})
            .sink("_aot_broken", {}))
    # operator construction fails -> error lands in the report (not an
    # exception mid-scheduling), naming the operator
    report = compile_program(prog)
    assert not report.ok
    assert any("cannot reach upstream" in e for e in report.errors)


def test_filesystem_format_typo_rejected_at_plan_time(tmp_path):
    import pytest as _pytest

    with _pytest.raises(Exception):
        (Stream.source("impulse", {"event_rate": 0.0, "message_count": 10})
         .sink("filesystem", {"path": f"file://{tmp_path}",
                              "format": "not-a-format"}))


def test_compile_program_invalid_graph():
    from arroyo_tpu.graph.logical import AggKind, AggSpec

    prog = (Stream.source("impulse", {"event_rate": 0.0, "message_count": 10})
            .key_by("counter")
            .tumbling_aggregate(1000, [AggSpec(AggKind.COUNT, None, "c")])
            .sink("blackhole", {}))
    # window without watermark: validation error surfaces in the report
    report = compile_program(prog)
    assert not report.ok


def test_serialize_step_roundtrip(tmp_path):
    import jax.numpy as jnp

    def step(x, y):
        return (x * y).sum(axis=0), x + 1

    x = jnp.arange(12, dtype=jnp.float32).reshape(3, 4)
    y = jnp.ones((3, 4), jnp.float32)
    data = serialize_step(step, (x, y))
    assert isinstance(data, (bytes, bytearray)) and len(data) > 100

    fn = deserialize_step(data)
    out_s, out_x = fn(x, y)
    np.testing.assert_allclose(np.asarray(out_s), np.asarray((x * y).sum(0)))

    # artifact-store roundtrip (compiler.rs:247-259 analog)
    url = f"file://{tmp_path}/artifacts"
    store_step(url, "flagship_step", data)
    fn2 = load_step(url, "flagship_step")
    np.testing.assert_allclose(np.asarray(fn2(x, y)[1]),
                               np.asarray(x + 1))


def test_enable_persistent_cache(tmp_path):
    d = enable_persistent_cache(str(tmp_path / "cache"))
    assert "cache" in d
