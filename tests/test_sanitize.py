"""arroyosan contract suite (PR 5).

Static half: the async-race pass flags the PR 3 shield race when the
shield is removed (and stays quiet on the shielded/finally/locked
variants and on the real autoscaler supervisor); the protocol pass
flags control-before-flush reorderings of the task loop.

Runtime half: one pinned fixture per invariant — violation injected ->
``SanitizerError`` carrying the offending event ring — plus end-to-end
paths through a real TaskRunner, and a seeded-interleaving fuzz that
drives checkpoint/rescale/barrier orderings through a sanitized engine
and requires zero violations."""

import ast
import asyncio
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from arroyo_tpu.analysis import async_race, protocol
from arroyo_tpu.analysis.sanitizer import (
    Sanitizer,
    SanitizerError,
    _reset_ring,
    maybe_sanitizer,
    recent_events,
    sanitize_enabled,
)
from arroyo_tpu.engine.context import Context
from arroyo_tpu.engine.operator import Operator
from arroyo_tpu.engine.task import TaskRunner
from arroyo_tpu.types import (
    Batch,
    Message,
    TaskInfo,
    Watermark,
)


def _run_race(src, path="arroyo_tpu/autoscale/fixture.py"):
    src = textwrap.dedent(src)
    return async_race.check(ast.parse(src), src.splitlines(), path)


# ---------------------------------------------------------------------------
# static: the PR 3 shield race class
# ---------------------------------------------------------------------------

# the PRE-hardening autoscaler supervisor shape: the loop task is
# cancelled by the disable toggle, and _do_rescale mutates _rescaling
# across the rescale await with neither shield nor finally — exactly
# the mid-rescale strand PR 3's review caught by hand
PR3_SHIELD_RACE = """
    import asyncio

    class JobAutoscaler:
        def __init__(self):
            self._task = None
            self._rescaling = False

        def start(self):
            self._task = asyncio.ensure_future(self._loop())

        def stop(self):
            if self._task is not None:
                self._task.cancel()

        async def _loop(self):
            while True:
                await asyncio.sleep(1)
                await self.evaluate_once()

        async def evaluate_once(self):
            if self._rescaling:
                return
            await self._actuate()

        async def _actuate(self):
            await self._do_rescale()

        async def _do_rescale(self):
            self._rescaling = True
            await self.controller.rescale_job("j", {})
            self._rescaling = False
"""


def test_async_race_flags_pr3_race_without_shield():
    findings = _run_race(PR3_SHIELD_RACE)
    codes = {f.code for f in findings}
    assert "cancel-window" in codes, findings
    f = next(f for f in findings if f.code == "cancel-window")
    assert "_rescaling" in f.message and "shield" in f.message


def test_async_race_quiet_with_shield():
    shielded = PR3_SHIELD_RACE.replace(
        "await self._do_rescale()",
        "await asyncio.shield(self._do_rescale())")
    assert _run_race(shielded) == []


def test_async_race_quiet_with_finally_recovery():
    hardened = PR3_SHIELD_RACE.replace(
        """            self._rescaling = True
            await self.controller.rescale_job("j", {})
            self._rescaling = False""",
        """            self._rescaling = True
            try:
                await self.controller.rescale_job("j", {})
            finally:
                self._rescaling = False""")
    assert _run_race(hardened) == []


CROSS_TASK_RACE = """
    import asyncio

    class Dispatcher:
        def __init__(self):
            self.inflight = 0

        def start(self):
            asyncio.ensure_future(self._pump_a())
            asyncio.ensure_future(self._pump_b())

        async def _pump_a(self):
            n = self.inflight
            await self.send()
            self.inflight = n + 1

        async def _pump_b(self):
            n = self.inflight
            await self.send()
            self.inflight = n - 1
"""


def test_async_race_flags_cross_task_rmw():
    findings = _run_race(CROSS_TASK_RACE,
                         "arroyo_tpu/engine/fixture.py")
    assert {f.code for f in findings} == {"cross-task-race"}
    f = findings[0]
    assert "inflight" in f.message and "_pump_a" in f.message


def test_async_race_lock_serializes_the_window():
    locked = CROSS_TASK_RACE.replace(
        "self.inflight = 0",
        "self.inflight = 0\n            self._lock = asyncio.Lock()"
    ).replace(
        """            n = self.inflight
            await self.send()
            self.inflight = n + 1""",
        """            async with self._lock:
                n = self.inflight
                await self.send()
                self.inflight = n + 1""").replace(
        """            n = self.inflight
            await self.send()
            self.inflight = n - 1""",
        """            async with self._lock:
                n = self.inflight
                await self.send()
                self.inflight = n - 1""")
    assert _run_race(locked, "arroyo_tpu/engine/fixture.py") == []


def test_async_race_out_of_scope_paths_skipped():
    # ops/ kernels have no task concurrency: same source, no findings
    assert _run_race(CROSS_TASK_RACE, "arroyo_tpu/ops/fixture.py") == []


def test_async_race_clean_on_real_supervisor():
    """The hardened autoscaler (shield + finally) must analyze clean —
    the pass validates the PR 3 fix, it does not re-flag it."""
    path = os.path.join(os.path.dirname(async_race.__file__), "..",
                        "autoscale", "supervisor.py")
    src = open(path).read()
    findings = async_race.check(
        ast.parse(src), src.splitlines(),
        "arroyo_tpu/autoscale/supervisor.py")
    assert findings == []


def test_async_race_flags_real_supervisor_when_shield_removed():
    """The acceptance pin: strip PR 3's two hardenings (the shield on
    the in-flight rescale and the finally-based recovery) from the REAL
    supervisor source — the pass must rediscover the race hand review
    caught."""
    path = os.path.join(os.path.dirname(async_race.__file__), "..",
                        "autoscale", "supervisor.py")
    src = open(path).read()
    mutated = src.replace(
        "await asyncio.shield(self._do_rescale(decision))",
        "await self._do_rescale(decision)").replace(
        "        finally:\n            self._rescaling = False\n",
        "        self._rescaling = False\n")
    assert mutated != src, "supervisor hardening shape changed; update test"
    findings = async_race.check(
        ast.parse(mutated), mutated.splitlines(),
        "arroyo_tpu/autoscale/supervisor.py")
    assert any(f.code == "cancel-window" and "_rescaling" in f.message
               for f in findings), findings


def test_async_race_cli_exits_nonzero_on_seeded_fixture(tmp_path):
    pkg = tmp_path / "arroyo_tpu" / "autoscale"
    pkg.mkdir(parents=True)
    fixture = pkg / "seeded.py"
    fixture.write_text(textwrap.dedent(PR3_SHIELD_RACE))
    r = subprocess.run(
        [sys.executable, "-m", "arroyo_tpu.analysis", "--no-baseline",
         "--pass", "async-race", str(fixture)],
        capture_output=True, text=True)
    assert r.returncode != 0, r.stdout + r.stderr
    assert "cancel-window" in r.stdout


# ---------------------------------------------------------------------------
# static: barrier/watermark protocol checker
# ---------------------------------------------------------------------------


def _run_protocol(src, path="arroyo_tpu/engine/fixture.py"):
    src = textwrap.dedent(src)
    return protocol.check(ast.parse(src), src.splitlines(), path)


BAD_LOOP = """
    from arroyo_tpu.types import MessageKind

    class Loop:
        async def run(self, msg, idx, coal):
            while True:
                if msg.kind == MessageKind.WATERMARK:
                    advanced = self.ctx.observe_watermark(idx, msg.watermark)
                    if coal.pending:
                        for s, b in coal.flush_all():
                            await self.process(b, s)
"""


def test_protocol_flags_control_before_flush():
    findings = _run_protocol(BAD_LOOP)
    assert {f.code for f in findings} == {"control-before-flush"}
    assert "watermark" in findings[0].message


def test_protocol_quiet_on_flush_first():
    good = textwrap.dedent("""
        from arroyo_tpu.types import MessageKind

        class Loop:
            async def run(self, msg, idx, coal):
                while True:
                    if msg.kind == MessageKind.WATERMARK:
                        if coal.pending:
                            for s, b in coal.flush_all():
                                await self.process(b, s)
                        advanced = self.ctx.observe_watermark(
                            idx, msg.watermark)
                    elif msg.is_end:
                        if coal.pending:
                            for s, b in coal.flush_all():
                                await self.process(b, s)
                        for e in self.ctx.counter.mark_closed(idx):
                            await self.run_checkpoint(e)
    """)
    assert protocol.check(ast.parse(good), good.splitlines(),
                          "arroyo_tpu/engine/fixture.py") == []


def test_protocol_flags_barrier_and_end_reorders():
    bad = textwrap.dedent("""
        from arroyo_tpu.types import MessageKind

        class Loop:
            async def run(self, msg, idx, coal):
                if msg.kind == MessageKind.BARRIER:
                    if self.ctx.counter.observe(idx, msg.barrier.epoch):
                        await self.run_checkpoint(msg.barrier)
                    for s, b in coal.flush_all():
                        await self.process(b, s)
    """)
    findings = protocol.check(ast.parse(bad), bad.splitlines(),
                              "arroyo_tpu/engine/fixture.py")
    assert [f.code for f in findings] == ["control-before-flush"]


def test_protocol_bufferless_handlers_exempt():
    src = textwrap.dedent("""
        from arroyo_tpu.types import MessageKind

        class Chain:
            async def _control(self, msg):
                if msg.kind == MessageKind.WATERMARK:
                    await self.tail_ctx.broadcast(msg)
    """)
    assert protocol.check(ast.parse(src), src.splitlines(),
                          "arroyo_tpu/engine/fixture.py") == []


def test_protocol_scope_is_engine_only():
    assert _run_protocol(BAD_LOOP, "arroyo_tpu/ops/fixture.py") == []


def test_protocol_nested_helper_is_its_own_scope():
    """A control branch inside a nested helper is evaluated against the
    HELPER's flush machine, not the enclosing function's — and is never
    reported twice."""
    src = textwrap.dedent("""
        from arroyo_tpu.types import MessageKind

        class Loop:
            async def run(self, msg, idx, coal):
                if coal.pending:
                    for s, b in coal.flush_all():
                        await self.process(b, s)

                async def helper(m):
                    # no buffer in THIS scope: exempt from the contract
                    if m.kind == MessageKind.WATERMARK:
                        advanced = self.ctx.observe_watermark(idx, m)

                await helper(msg)
    """)
    assert protocol.check(ast.parse(src), src.splitlines(),
                          "arroyo_tpu/engine/fixture.py") == []


def test_async_race_nonlock_async_with_is_await_point():
    """`async with` suspends in __aenter__/__aexit__ even when the
    context is not a lock — a mutation window spanning it must count."""
    src = """
        import asyncio

        class Fetcher:
            def __init__(self):
                self._task = None
                self.phase = ""

            def start(self):
                self._task = asyncio.ensure_future(self._loop())

            def stop(self):
                self._task.cancel()

            async def _loop(self):
                self.phase = "connecting"
                async with self.client.stream("u") as r:
                    pass
                self.phase = "done"
    """
    findings = _run_race(src, "arroyo_tpu/network/fixture.py")
    assert any(f.code == "cancel-window" and "phase" in f.message
               for f in findings), findings


def test_real_task_loop_is_protocol_clean():
    import arroyo_tpu.engine.task as task_mod

    path = task_mod.__file__
    src = open(path).read()
    assert protocol.check(ast.parse(src), src.splitlines(),
                          "arroyo_tpu/engine/task.py") == []


# ---------------------------------------------------------------------------
# runtime: one pinned fixture per invariant
# ---------------------------------------------------------------------------


@pytest.fixture(autouse=True)
def _clean_ring():
    _reset_ring()
    yield


def _batch(n=4, cols=("a",)):
    return Batch(np.arange(n, dtype=np.int64),
                 {c: np.arange(n) for c in cols})


def test_enable_knob_and_off_is_none(monkeypatch):
    monkeypatch.setenv("ARROYO_SANITIZE", "0")
    assert not sanitize_enabled()
    assert maybe_sanitizer() is None
    monkeypatch.setenv("ARROYO_SANITIZE", "1")
    assert sanitize_enabled()
    assert isinstance(maybe_sanitizer(), Sanitizer)


def test_watermark_regression_raises_with_event_ring():
    san = Sanitizer()
    san.on_watermark(("t-0", 0), Watermark.event_time(100))
    with pytest.raises(SanitizerError) as ei:
        san.on_watermark(("t-0", 0), Watermark.event_time(50))
    err = ei.value
    assert err.code == "watermark-regression"
    assert "arroyosan[watermark-regression]" in str(err)
    # the ring carries the offending sequence, oldest first
    kinds = [e[1] for e in err.events]
    assert kinds.count("watermark") >= 2


def test_watermark_idle_and_per_edge_isolation():
    san = Sanitizer()
    san.on_watermark(("t-0", 0), Watermark.event_time(100))
    san.on_watermark(("t-0", 0), Watermark.idle())  # idle never regresses
    san.on_watermark(("t-0", 0), Watermark.event_time(100))  # equal ok
    san.on_watermark(("t-0", 1), Watermark.event_time(10))  # other edge
    assert san.violations == 0


def test_schema_instability_raises_but_dtype_promotion_allowed():
    san = Sanitizer()
    edge = ("t-1", 0)
    san.on_record(edge, _batch(cols=("a", "b")))
    # dtype drift is numpy-concat-legal; names are the contract
    b2 = Batch(np.arange(3, dtype=np.int64),
               {"a": np.arange(3.0), "b": np.arange(3)})
    san.on_record(edge, b2)
    with pytest.raises(SanitizerError) as ei:
        san.on_record(edge, _batch(cols=("a", "c")))
    assert ei.value.code == "schema-instability"


def test_sharding_instability_raises_on_flip():
    """The sharded-data-plane invariant: an edge that routed on-device
    must not silently fall back to the host route mid-stream (or vice
    versa) — the resharding analogue of the column-layout check."""
    san = Sanitizer()
    edge = ("opX", 0)
    san.on_sharding(edge, "keys@4")
    san.on_sharding(edge, "keys@4")  # stable: fine
    san.on_sharding(("opY", 0), "host@4")  # other edge: independent
    with pytest.raises(SanitizerError) as ei:
        san.on_sharding(edge, "host@4")
    assert ei.value.code == "sharding-instability"
    assert any(e[1] == "sharding" for e in ei.value.events)


def test_sharding_instability_engine_injected(monkeypatch, rng):
    """Injected violation through the REAL Collector: force a device-
    routed edge, then break the DeviceShuffle's stickiness so the next
    batch takes the host route — the sanitizer must raise."""
    import asyncio as aio

    from arroyo_tpu.engine.context import Collector, OutQueue
    from arroyo_tpu.types import hash_columns

    monkeypatch.setenv("ARROYO_SHUFFLE_DEVICE", "on")
    keys = rng.integers(0, 64, 500).astype(np.int64)
    kh = hash_columns([keys])
    b = Batch(np.zeros(500, np.int64), {"k": keys}, kh, ("k",))
    san = Sanitizer("inject")
    qs = [aio.Queue(maxsize=100) for _ in range(4)]
    coll = Collector([[OutQueue(queue=q) for q in qs]],
                     op_id="opZ", sanitizer=san)

    async def scenario():
        await coll.collect(b)
        # sabotage: disable the device path mid-stream (the stickiness
        # DeviceShuffle guarantees, deliberately broken)
        coll._dev_shuffle[0] = None
        await coll.collect(b)

    with pytest.raises(SanitizerError) as ei:
        aio.run(scenario())
    assert ei.value.code == "sharding-instability"


def test_barrier_crossing_detection():
    class Counter:
        seen = {7: {0}}

    san = Sanitizer()
    san.on_record_during_alignment("t-2", 1, Counter())  # other input ok
    with pytest.raises(SanitizerError) as ei:
        san.on_record_during_alignment("t-2", 0, Counter())
    assert ei.value.code == "barrier-crossing"
    assert "epoch 7" in str(ei.value)


def test_coalesce_unflushed_raises():
    class Pending:
        pending = True

    class Drained:
        pending = False

    san = Sanitizer()
    san.before_control("t-3", "watermark", Drained())
    san.before_control("t-3", "watermark", None)
    with pytest.raises(SanitizerError) as ei:
        san.before_control("t-3", "barrier", Pending())
    assert ei.value.code == "coalesce-unflushed"


def test_duplicate_checkpoint_completion_raises():
    san = Sanitizer()
    san.on_checkpoint_completed("op-1", 0, 1)
    san.on_checkpoint_completed("op-1", 1, 1)  # other subtask ok
    san.on_checkpoint_completed("op-1", 0, 2)  # next epoch ok
    with pytest.raises(SanitizerError) as ei:
        san.on_checkpoint_completed("op-1", 0, 1)
    assert ei.value.code == "duplicate-checkpoint"


def test_mutation_during_checkpoint_raises_through_real_store():
    from arroyo_tpu.state.backend import InMemoryBackend
    from arroyo_tpu.state.store import StateStore

    class MutatingBackend(InMemoryBackend):
        """Models an upload path that touches live state."""

        def __init__(self, store_ref):
            super().__init__()
            self.store_ref = store_ref

        def write_subtask_checkpoint(self, task, epoch, tables, wm):
            st = self.store_ref[0]
            st.get_global_keyed_state("g").insert("sneak", 1)
            return super().write_subtask_checkpoint(
                task, epoch, tables, wm)

    ref = []
    ti = TaskInfo("job", "op-0", "op", 0, 1)
    store = StateStore(ti, MutatingBackend(ref))
    ref.append(store)
    store.sanitizer = Sanitizer()
    store.get_global_keyed_state("g").insert("k", 42)
    with pytest.raises(SanitizerError) as ei:
        store.checkpoint(1, None)
    assert ei.value.code == "mutation-during-checkpoint"
    assert "'g'" in str(ei.value) or "g" in str(ei.value)

    # and a clean store checkpoints fine with the sanitizer armed
    clean = StateStore.new_in_memory(ti)
    clean.sanitizer = Sanitizer()
    clean.get_global_keyed_state("g").insert("k", 42)
    meta = clean.checkpoint(2, None)
    assert meta.epoch == 2


def test_controller_flags_duplicate_completion_in_one_tracker(run_async):
    """The controller-side half of checkpoint completeness: a duplicate
    (operator, subtask) completion within one live tracker raises (the
    tracker itself is cleared on restart/rescale, so restarts never
    false-positive)."""
    from arroyo_tpu import Stream
    from arroyo_tpu.controller.controller import ControllerServer, Job

    ctrl = ControllerServer.__new__(ControllerServer)
    ctrl.sanitizer = Sanitizer("controller")
    prog = Stream.source("impulse", {"message_count": 10}).sink(
        "blackhole", {})
    job = Job("dup", prog, "file:///tmp/dup-ckpt", 1)
    job.n_subtasks = 10  # keep the tracker open (no finalize path)
    ctrl.jobs = {"dup": job}
    req = {"job_id": "dup", "epoch": 1, "operator_id": "op-0",
           "subtask": 0}

    async def go():
        await ctrl._task_ckpt_completed(dict(req))
        await ctrl._task_ckpt_completed(
            {**req, "subtask": 1})  # sibling fine
        with pytest.raises(SanitizerError) as ei:
            await ctrl._task_ckpt_completed(dict(req))
        assert ei.value.code == "duplicate-checkpoint"
        # a cleared tracker (restart) resets the slate
        job.trackers.clear()
        await ctrl._task_ckpt_completed(dict(req))

    run_async(go())


def test_admin_sanitizer_endpoint(run_async):
    import httpx

    from arroyo_tpu.obs.admin import AdminServer

    async def go():
        san = Sanitizer()
        san.event("watermark", "op-0-0", 123)
        admin = AdminServer("worker")
        port = await admin.start()
        try:
            async with httpx.AsyncClient(
                    base_url=f"http://127.0.0.1:{port}") as c:
                r = await c.get("/sanitizer")
                body = r.json()
                assert body["enabled"] is True  # conftest arms tier-1
                assert any(e["kind"] == "watermark"
                           for e in body["events"])
        finally:
            await admin.stop()

    run_async(go())


# ---------------------------------------------------------------------------
# runtime: violations surface through a real TaskRunner
# ---------------------------------------------------------------------------


class _Collect(Operator):
    def __init__(self):
        super().__init__("collect")
        self.rows = 0

    async def process_batch(self, batch, ctx, side=0):
        self.rows += len(batch)
        await ctx.collect(batch)


def _runner(op, san, n_inputs=1):
    ctx, outq = Context.new_for_test(n_inputs=n_inputs)
    inq: asyncio.Queue = asyncio.Queue()
    runner = TaskRunner(ctx.task_info, op, ctx, [(0, inq)],
                        asyncio.Queue(), asyncio.Queue(), sanitizer=san)
    return runner, inq, outq


def test_task_runner_fails_task_on_watermark_regression(run_async):
    async def go():
        op = _Collect()
        runner, inq, _ = _runner(op, Sanitizer())
        t = asyncio.ensure_future(runner.start())
        await inq.put(Message.wm(Watermark.event_time(1_000)))
        await inq.put(Message.wm(Watermark.event_time(500)))
        await inq.put(Message.end_of_data())
        await asyncio.wait_for(runner.finished.wait(), 10)
        await t
        return runner

    runner = run_async(go())
    assert isinstance(runner.failed, SanitizerError)
    assert runner.failed.code == "watermark-regression"


def test_task_runner_clean_run_records_events_no_violations(run_async):
    async def go():
        op = _Collect()
        san = Sanitizer()
        runner, inq, _ = _runner(op, san)
        t = asyncio.ensure_future(runner.start())
        await inq.put(Message.record(_batch()))
        await inq.put(Message.wm(Watermark.event_time(1_000)))
        await inq.put(Message.record(_batch()))
        await inq.put(Message.wm(Watermark.event_time(2_000)))
        await inq.put(Message.end_of_data())
        await asyncio.wait_for(runner.finished.wait(), 10)
        await t
        return runner, san, op

    runner, san, op = run_async(go())
    assert runner.failed is None
    assert op.rows == 8
    assert san.violations == 0
    kinds = {e[1] for e in recent_events(256)}
    assert {"watermark", "schema", "control"} <= kinds


def test_task_runner_catches_record_crossing_barrier(run_async):
    async def go():
        op = _Collect()
        runner, inq, _ = _runner(op, Sanitizer())
        # forge a partially-aligned barrier: input 0 already delivered
        # its barrier for epoch 3 (a healthy pump would now be parked)
        runner.ctx.counter.seen = {3: {0}}
        t = asyncio.ensure_future(runner.start())
        await inq.put(Message.record(_batch()))
        await inq.put(Message.end_of_data())
        await asyncio.wait_for(runner.finished.wait(), 10)
        await t
        return runner

    runner = run_async(go())
    assert isinstance(runner.failed, SanitizerError)
    assert runner.failed.code == "barrier-crossing"


def test_engine_off_means_no_sanitizer(monkeypatch):
    """ARROYO_SANITIZE=0 steady state: the engine wires None into every
    hook site (the zero-overhead contract bench.py measures)."""
    from arroyo_tpu import Stream
    from arroyo_tpu.connectors.memory import clear_sink, sink_output
    from arroyo_tpu.engine.engine import LocalRunner

    monkeypatch.setenv("ARROYO_SANITIZE", "0")
    clear_sink("san_off")
    prog = Stream.source("impulse", {"event_rate": 0.0,
                                     "message_count": 500,
                                     "batch_size": 64}).sink(
        "memory", {"name": "san_off"})
    runner = LocalRunner(prog)
    runner.run()
    assert runner.engine.sanitizer is None
    assert sum(len(b) for b in sink_output("san_off")) == 500


# ---------------------------------------------------------------------------
# seeded-interleaving fuzz: checkpoint/rescale/barrier orderings
# ---------------------------------------------------------------------------


def _keyed_prog(sink_name, n=30_000, event_rate=0.0):
    from arroyo_tpu import Stream

    return (
        Stream.source("impulse", {"event_rate": event_rate,
                                  "message_count": n,
                                  "batch_size": 256}, parallelism=2)
        .map(lambda c: {"counter": c["counter"],
                        "k": c["counter"] % 17}, name="keyer")
        .key_by("k")
        .count()
        .sink("memory", {"name": sink_name}, parallelism=1)
    )


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_fuzz_checkpoint_orderings_sanitized(seed, monkeypatch):
    """Seeded interleavings: inject 1-3 checkpoint barriers at random
    times (sometimes racing each other closely) into a running sanitized
    engine; the run must complete with zero invariant violations and
    full output."""
    from arroyo_tpu.connectors.memory import clear_sink, sink_output
    from arroyo_tpu.engine.engine import Engine

    monkeypatch.setenv("ARROYO_SANITIZE", "1")
    rng = np.random.default_rng(seed)
    name = f"fuzz_{seed}"
    clear_sink(name)
    prog = _keyed_prog(name)

    async def go():
        engine = Engine.for_local(prog, f"fuzz-{seed}")
        running = engine.start()
        epoch = 0
        for _ in range(int(rng.integers(1, 4))):
            await asyncio.sleep(float(rng.uniform(0.01, 0.15)))
            epoch += 1
            await running.checkpoint(epoch)
        await asyncio.wait_for(running.join(), 60)
        return engine

    engine = asyncio.run(go())
    assert engine.sanitizer is not None
    assert engine.sanitizer.violations == 0
    rows = sum(len(b) for b in sink_output(name))
    assert rows > 0


@pytest.mark.parametrize("seed", [3, 4])
def test_fuzz_checkpoint_stop_restore_rescale_sanitized(
        seed, tmp_path, monkeypatch):
    """The rescale ordering: checkpoint-then-stop mid-stream at a
    seeded time, restore at a different parallelism — both sanitized
    engine runs must see zero violations."""
    from arroyo_tpu.connectors.memory import clear_sink, sink_output
    from arroyo_tpu.engine.engine import Engine

    monkeypatch.setenv("ARROYO_SANITIZE", "1")
    rng = np.random.default_rng(seed)
    name = f"fuzz_rs_{seed}"
    # warm the jit caches with a tiny run of the same shapes first: on a
    # cold cache, compilation would otherwise eat the checkpoint window
    # and flake the barrier wait
    from arroyo_tpu.engine.engine import LocalRunner

    clear_sink(name)
    LocalRunner(_keyed_prog(name, n=2_000)).run()
    clear_sink(name)
    # RATE-LIMITED so the stream deterministically outlives the seeded
    # injection point (<= 0.12s): the old unthrottled 200k-event run
    # relied on the box being slow enough, and the vectorized ingest
    # path made it drain in ~0.05s warm — a finished job has no
    # sources left to accept the barrier, and the checkpoint wait
    # correctly reports False (same deflake pattern as PR 10's
    # rate-limited join restore test)
    prog = _keyed_prog(name, n=60_000, event_rate=50_000.0)
    url = f"file://{tmp_path}/ckpt"

    async def phase1():
        engine = Engine.for_local(prog, f"fuzz-rs-{seed}",
                                  checkpoint_url=url)
        running = engine.start()
        await asyncio.sleep(float(rng.uniform(0.02, 0.12)))
        await running.checkpoint(epoch=1, then_stop=True)
        assert await running.wait_for_checkpoint(1, timeout=120)
        try:
            await asyncio.wait_for(running.join(), 60)
        except RuntimeError:
            pass
        return engine

    e1 = asyncio.run(phase1())
    assert e1.sanitizer is not None and e1.sanitizer.violations == 0

    # restore with the keyed aggregate rescaled 2 -> 3
    agg_id = next(nd.operator_id for nd in prog.nodes()
                  if "count" in nd.operator_id.lower()
                  or "agg" in nd.operator_id.lower())
    from arroyo_tpu.graph.chaining import expand_overrides

    prog.update_parallelism(expand_overrides(prog, {agg_id: 3}))

    async def phase2():
        engine = Engine.for_local(prog, f"fuzz-rs-{seed}",
                                  checkpoint_url=url, restore_epoch=1)
        running = engine.start()
        await asyncio.wait_for(running.join(), 60)
        return engine

    e2 = asyncio.run(phase2())
    assert e2.sanitizer is not None and e2.sanitizer.violations == 0
    assert sum(len(b) for b in sink_output(name)) > 0
