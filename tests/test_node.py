"""Node-daemon cluster: NodeScheduler places workers on node daemons
(arroyo-node/src/main.rs:44-319 analog); the daemons spawn worker OS
processes, reap them, and report WorkerFinished."""

import asyncio

import pytest

from arroyo_tpu import Stream
from arroyo_tpu.controller.controller import ControllerServer
from arroyo_tpu.controller.scheduler import NodeScheduler
from arroyo_tpu.controller.state_machine import JobState
from arroyo_tpu.graph.logical import AggKind, AggSpec
from arroyo_tpu.node import NodeServer


@pytest.mark.slow
def test_node_daemon_cluster(tmp_path):
    out_path = tmp_path / "out.jsonl"

    async def scenario():
        node1, node2 = NodeServer(), NodeServer()
        a1, a2 = await node1.start(), await node2.start()
        sched = NodeScheduler([a1, a2])
        ctrl = ControllerServer(sched)
        await ctrl.start()
        prog = (
            Stream.source("impulse", {"event_rate": 0.0,
                                      "message_count": 2000,
                                      "event_time_interval_micros": 1000,
                                      "batch_size": 128}, parallelism=2)
            .watermark(max_lateness_micros=0)
            .map(lambda c: {"counter": c["counter"],
                            "bucket": c["counter"] % 5}, name="b")
            .key_by("bucket")
            .tumbling_aggregate(
                250 * 1000, [AggSpec(AggKind.COUNT, None, "cnt")],
                parallelism=2)
            .sink("single_file", {"path": str(out_path)}, parallelism=1)
        )
        job_id = await ctrl.submit_job(
            prog, checkpoint_url=f"file://{tmp_path}/ckpt", n_workers=2)
        try:
            # one worker per node daemon, both register with the
            # controller.  The window is generous (90s) on purpose:
            # each worker is a real OS process that imports jax under
            # the suite's 8-fake-device mesh, and on a loaded box two
            # cold interpreter starts have measured past the old 30s
            # cap — which made this test the suite's load flake while
            # it passed every time in isolation.  A healthy run exits
            # the poll in a couple of seconds either way.
            for _ in range(900):
                if len(ctrl.jobs[job_id].workers) >= 2:
                    break
                await asyncio.sleep(0.1)
            assert len(ctrl.jobs[job_id].workers) >= 2, "workers never came"
            assert len(sched.workers_for_job(job_id)) == 2
            w1 = await node1._get_workers({})
            w2 = await node2._get_workers({})
            assert len(w1["worker_ids"]) == 1  # round-robin placement
            assert len(w2["worker_ids"]) == 1
            state = await ctrl.wait_for_state(job_id, JobState.FINISHED,
                                              timeout=120)
        finally:
            await sched.stop_workers(job_id)
            await ctrl.stop()
            await node1.stop()
            await node2.stop()
        return state

    state = asyncio.run(scenario())
    assert state == JobState.FINISHED
