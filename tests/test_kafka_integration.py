"""Kafka integration against a REAL broker plus the schema-registry
client.

The real-broker tests mirror the reference's kafka tests
(kafka/source/test.rs:28-100: spin a topic on a local broker, run the
source, checkpoint, restart, assert exactly-once).  No broker ships in
this image, so they are marked ``kafka`` and skip unless
``KAFKA_BOOTSTRAP`` points at one (`pytest -m kafka`).

The schema-registry client tests run everywhere: a stdlib fake registry
serves the Confluent REST surface in-process.
"""

import json
import os
import threading

import numpy as np
import pytest

KAFKA_BOOTSTRAP = os.environ.get("KAFKA_BOOTSTRAP")

needs_broker = pytest.mark.skipif(
    not KAFKA_BOOTSTRAP,
    reason="set KAFKA_BOOTSTRAP=host:port to run real-broker tests")


# ---------------------------------------------------------------------------
# schema registry (runs everywhere)
# ---------------------------------------------------------------------------


class _FakeRegistry:
    """Threaded stdlib HTTP server speaking the two Confluent endpoints
    the client uses."""

    def __init__(self):
        from http.server import BaseHTTPRequestHandler, HTTPServer

        reg = self
        reg.schemas = {}  # id -> schema text
        reg.subjects = {}  # (subject, text) -> id
        reg.next_id = 1

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _send(self, code, obj):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                parts = self.path.strip("/").split("/")
                if (len(parts) == 3 and parts[0] == "subjects"
                        and parts[2] == "versions"):
                    n = int(self.headers["Content-Length"])
                    payload = json.loads(self.rfile.read(n))
                    key = (parts[1], payload["schema"])
                    if key not in reg.subjects:
                        reg.subjects[key] = reg.next_id
                        reg.schemas[reg.next_id] = payload["schema"]
                        reg.next_id += 1
                    self._send(200, {"id": reg.subjects[key]})
                else:
                    self._send(404, {"error_code": 404})

            def do_GET(self):
                parts = self.path.strip("/").split("/")
                if (len(parts) == 3 and parts[0] == "schemas"
                        and parts[1] == "ids"):
                    sid = int(parts[2])
                    if sid in reg.schemas:
                        self._send(200, {"schema": reg.schemas[sid]})
                    else:
                        self._send(404, {"error_code": 40403})
                else:
                    self._send(404, {"error_code": 404})

        self.server = HTTPServer(("127.0.0.1", 0), Handler)
        self.url = f"http://127.0.0.1:{self.server.server_port}"
        self._th = threading.Thread(target=self.server.serve_forever,
                                    daemon=True)
        self._th.start()

    def close(self):
        self.server.shutdown()


@pytest.fixture
def fake_registry():
    r = _FakeRegistry()
    yield r
    r.close()


def test_registry_client_register_and_fetch(fake_registry):
    from arroyo_tpu.connectors.schema_registry import SchemaRegistryClient

    c = SchemaRegistryClient(fake_registry.url)
    schema = {"type": "record", "name": "ev", "fields": [
        {"name": "k", "type": ["null", "long"]}]}
    sid = c.register("ev-value", schema)
    assert sid == 1
    assert c.register("ev-value", schema) == 1  # idempotent (cached)
    got = c.get_schema(sid)
    assert got["name"] == "ev"
    # a second, evolved schema gets a new id
    schema2 = {"type": "record", "name": "ev", "fields": [
        {"name": "k", "type": ["null", "long"]},
        {"name": "v", "type": ["null", "double"]}]}
    assert c.register("ev-value", schema2) == 2


def test_registry_client_errors(fake_registry):
    from arroyo_tpu.connectors.schema_registry import (
        SchemaRegistryClient,
        SchemaRegistryError,
    )

    c = SchemaRegistryClient(fake_registry.url)
    with pytest.raises(SchemaRegistryError, match="404"):
        c.get_schema(99)
    dead = SchemaRegistryClient("http://127.0.0.1:1")
    with pytest.raises(SchemaRegistryError, match="failed"):
        dead.get_schema(1)


def test_avro_confluent_roundtrip_via_registry(fake_registry):
    """Producer registers its schema (id in the wire header); a consumer
    configured ONLY with the registry URL resolves the writer schema
    from the header — including after schema evolution mid-stream."""
    from arroyo_tpu.formats import AvroFormat

    schema_v1 = {"type": "record", "name": "ev", "fields": [
        {"name": "k", "type": ["null", "long"]}]}
    w1 = AvroFormat(schema=schema_v1, schema_registry_url=fake_registry.url,
                    subject="ev-value")
    payloads = w1.serialize([{"k": 1}, {"k": 2}])
    assert all(p[0] == 0 for p in payloads)  # confluent magic byte

    schema_v2 = {"type": "record", "name": "ev", "fields": [
        {"name": "k", "type": ["null", "long"]},
        {"name": "v", "type": ["null", "double"]}]}
    w2 = AvroFormat(schema=schema_v2, schema_registry_url=fake_registry.url,
                    subject="ev-value")
    payloads += w2.serialize([{"k": 3, "v": 1.5}])

    # reader has NO schema — only the registry
    r = AvroFormat(schema_registry_url=fake_registry.url)
    rows = r.deserialize(payloads)
    assert rows == [{"k": 1}, {"k": 2}, {"k": 3, "v": 1.5}]


def test_avro_without_schema_or_registry_rejected():
    from arroyo_tpu.formats import AvroFormat

    f = AvroFormat(confluent_schema_registry=True)
    with pytest.raises(ValueError, match="schema"):
        f.deserialize([b"\x00\x00\x00\x00\x01\x02"])


# ---------------------------------------------------------------------------
# real broker (pytest -m kafka; KAFKA_BOOTSTRAP required)
# ---------------------------------------------------------------------------


def _require_aiokafka():
    try:
        import aiokafka  # noqa: F401
    except ImportError:
        pytest.skip("aiokafka not installed (pip install aiokafka)")


@needs_broker
@pytest.mark.kafka
def test_real_broker_source_exactly_once(tmp_path):
    """kafka/source/test.rs analog: produce to a real topic, run the
    source with a mid-stream checkpoint, restart from it, and assert the
    offsets resume exactly-once."""
    _require_aiokafka()
    import asyncio
    import uuid

    from arroyo_tpu import Stream
    from arroyo_tpu.engine.engine import Engine
    from arroyo_tpu.connectors.memory import clear_sink, sink_output
    from arroyo_tpu.types import Batch, StopMode

    topic = f"arroyo-test-{uuid.uuid4().hex[:8]}"
    n1, n2 = 40, 25

    async def produce(values):
        from aiokafka import AIOKafkaProducer

        prod = AIOKafkaProducer(bootstrap_servers=KAFKA_BOOTSTRAP)
        await prod.start()
        try:
            for v in values:
                await prod.send_and_wait(
                    topic, json.dumps({"v": v}).encode())
        finally:
            await prod.stop()

    def prog():
        return (Stream.source("kafka", {
                    "bootstrap_servers": KAFKA_BOOTSTRAP, "topic": topic,
                    "group_id": f"g-{topic}", "format": "json",
                    "batch_size": 8})
                .map(lambda c: {"v": c["v"]}, name="m")
                .sink("memory", {"name": "results"}))

    async def phase1():
        await produce(range(n1))
        eng = Engine.for_local(prog(), "kafka-e1",
                               checkpoint_url=f"file://{tmp_path}/ckpt")
        running = eng.start()
        await asyncio.sleep(3.0)
        await running.checkpoint(1)
        assert await running.wait_for_checkpoint(1)
        await running.stop(StopMode.IMMEDIATE)
        try:
            await running.join()
        except RuntimeError:
            pass

    async def phase2():
        await produce(range(n1, n1 + n2))
        eng = Engine.for_local(prog(), "kafka-e1",
                               checkpoint_url=f"file://{tmp_path}/ckpt",
                               restore_epoch=1)
        running = eng.start()
        await asyncio.sleep(3.0)
        await running.stop(StopMode.IMMEDIATE)
        try:
            await running.join()
        except RuntimeError:
            pass

    clear_sink("results")
    asyncio.run(phase1())
    seen1 = sorted(int(v) for b in sink_output("results")
                   for v in np.asarray(b.columns["v"]).tolist())
    clear_sink("results")
    asyncio.run(phase2())
    seen2 = sorted(int(v) for b in sink_output("results")
                   for v in np.asarray(b.columns["v"]).tolist())
    # exactly-once: nothing consumed before the checkpoint reappears after
    # the restore, and everything produced is seen exactly once overall
    assert not (set(seen1) & set(seen2))
    assert sorted(seen1 + seen2) == list(range(n1 + n2))


@needs_broker
@pytest.mark.kafka
def test_real_broker_transactional_sink(tmp_path):
    """Transactional sink: rows only become visible to a read_committed
    consumer after the checkpoint's commit phase."""
    _require_aiokafka()
    import asyncio
    import uuid

    from arroyo_tpu import Stream
    from arroyo_tpu.engine.engine import LocalRunner

    topic = f"arroyo-sink-{uuid.uuid4().hex[:8]}"
    prog = (Stream.source("impulse", {"event_rate": 0.0,
                                      "message_count": 50,
                                      "batch_size": 16})
            .map(lambda c: {"counter": c["counter"]}, name="m")
            .sink("kafka", {"bootstrap_servers": KAFKA_BOOTSTRAP,
                            "topic": topic, "format": "json"}))
    LocalRunner(prog, checkpoint_url=f"file://{tmp_path}/ckpt").run(
        checkpoint_interval_secs=0.5)

    async def consume():
        from aiokafka import AIOKafkaConsumer

        cons = AIOKafkaConsumer(
            topic, bootstrap_servers=KAFKA_BOOTSTRAP,
            auto_offset_reset="earliest", isolation_level="read_committed",
            consumer_timeout_ms=5000)
        await cons.start()
        vals = []
        try:
            async for msg in cons:
                vals.append(json.loads(msg.value)["counter"])
                if len(vals) >= 50:
                    break
        finally:
            await cons.stop()
        return vals

    vals = asyncio.run(consume())
    assert sorted(vals) == list(range(50))
