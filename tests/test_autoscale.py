"""Autoscaler suite: deterministic policy-simulator tests (no workers,
fake clock), supervisor unit tests against a controller double, the REST
surface, and one in-process e2e where injected load drives a live
``rescale_job`` through the real controller."""

import asyncio
import json

import httpx
import pytest

import arroyo_tpu.config as cfg_mod
from arroyo_tpu import AggKind, AggSpec, Stream
from arroyo_tpu.autoscale import (
    BacklogDrainPolicy,
    EvalInput,
    JobAutoscaler,
    PolicyConfig,
)
from arroyo_tpu.autoscale.policy import (
    SCALE_DOWN,
    SCALE_UP,
    VETO,
    VETO_BUDGET,
    VETO_STALE,
)
from arroyo_tpu.autoscale.sim import (
    PolicySimulator,
    SimCluster,
    SimOperator,
    constant,
    drain,
    ramp,
    replay,
    square_wave,
)

# ---------------------------------------------------------------------------
# policy simulator (deterministic, fake clock, no workers)
# ---------------------------------------------------------------------------


def chain_cluster(agg_capacity=10_000.0, agg_p=1):
    """src -> map -> agg -> sink with the aggregate as the weak stage."""
    return SimCluster([
        SimOperator("src", 1e9),
        SimOperator("map", 50_000.0),
        SimOperator("agg", agg_capacity, parallelism=agg_p),
        SimOperator("sink", 1e9),
    ])


def make_cfg(**kw):
    base = dict(interval_secs=10.0, up_sustain=2, down_sustain=3,
                up_cooldown_secs=30.0, down_cooldown_secs=60.0,
                max_parallelism=8)
    base.update(kw)
    return PolicyConfig(**base)


def test_scale_up_bottleneck_only_on_sustained_backpressure():
    sim = PolicySimulator(BacklogDrainPolicy(make_cfg()), chain_cluster())
    res = sim.run(ramp(5_000, 30_000, over_secs=60), steps=12)
    ups = [d for d in res.actuations if d.action == SCALE_UP]
    assert ups, "sustained overload never scaled up"
    # bottleneck-aware: only the weak operator family scales
    assert {d.operator_id for d in ups} == {"agg"}
    assert sim.cluster.parallelism["src"] == 1
    assert sim.cluster.parallelism["map"] == 1
    assert sim.cluster.parallelism["sink"] == 1
    assert sim.cluster.parallelism["agg"] > 1


def test_no_scale_up_before_sustain():
    """One hot evaluation is noise; up_sustain evals are required."""
    pol = BacklogDrainPolicy(make_cfg(up_sustain=3))
    sim = PolicySimulator(pol, chain_cluster())
    # overload appears at t=0: first two hot evals must hold
    d1 = sim.step(constant(30_000))
    d2 = sim.step(constant(30_000))
    d3 = sim.step(constant(30_000))
    assert d1.action == "hold" and d2.action == "hold"
    assert d3.action == SCALE_UP and d3.operator_id == "agg"


def test_scale_up_respects_max_step_factor_and_bounds():
    pol = BacklogDrainPolicy(make_cfg(up_sustain=1, max_step_factor=2.0,
                                      per_op={"agg": {"min": 1, "max": 3}}))
    sim = PolicySimulator(pol, chain_cluster())
    d = sim.step(constant(80_000))  # 8x overload
    assert d.action == SCALE_UP
    assert d.to_parallelism <= 2  # at most doubled in one action
    sim.step(constant(80_000))
    for _ in range(20):
        sim.step(constant(80_000))
    assert sim.cluster.parallelism["agg"] == 3  # per-op ceiling holds


def test_scale_down_only_after_drain_and_cooldown():
    pol = BacklogDrainPolicy(make_cfg())
    sim = PolicySimulator(pol, chain_cluster())
    res = sim.run(drain(30_000, 2_000, until=120), steps=40)
    ups = [d for d in res.actuations if d.action == SCALE_UP]
    downs = [d for d in res.actuations if d.action == SCALE_DOWN]
    assert ups and downs
    last_up = max(d.t for d in ups)
    first_down = min(d.t for d in downs)
    # cooldown: no down within down_cooldown of the previous action
    assert first_down - last_up >= pol.cfg.down_cooldown_secs
    # drain: every down happened with the backlog drained
    for d in downs:
        assert d.inputs["agg"]["lag"] <= pol.cfg.drain_lag_secs
    # and the pipeline eventually returns to its floor
    assert sim.cluster.parallelism["agg"] == 1


def test_square_wave_no_flapping():
    """A load square wave must not bounce parallelism: with down_sustain
    spanning more than the low phase, the policy parks at the high-water
    mark — at most one direction change per period."""
    period = 240.0
    pol = BacklogDrainPolicy(make_cfg(down_sustain=18))  # 180s > low phase
    sim = PolicySimulator(pol, chain_cluster())
    steps = int(5 * period / pol.cfg.interval_secs)
    res = sim.run(square_wave(2_000, 25_000, period), steps=steps)
    assert res.actuations, "never scaled at all"
    periods = steps * pol.cfg.interval_secs / period
    assert res.direction_changes() <= periods
    # steady state: pinned at peak, not oscillating
    assert sim.cluster.parallelism["agg"] == max(
        d.to_parallelism for d in res.actuations)


def test_skewed_operator_scales_alone():
    """Fan-out DAG where one branch is hot: only that branch's operator
    scales (the PanJoin skew scenario)."""
    cluster = SimCluster(
        [SimOperator("src", 1e9),
         SimOperator("hot", 8_000.0),
         SimOperator("cold", 1e9),
         SimOperator("sink", 1e9)],
        upstream={"src": [], "hot": ["src"], "cold": ["src"],
                  "sink": ["hot", "cold"]})
    sim = PolicySimulator(BacklogDrainPolicy(make_cfg()), cluster)
    res = sim.run(constant(24_000), steps=12)
    ups = [d for d in res.actuations if d.action == SCALE_UP]
    assert ups and {d.operator_id for d in ups} == {"hot"}
    assert sim.cluster.parallelism["cold"] == 1
    assert sim.cluster.parallelism["hot"] > 1


def test_slot_budget_clamps_and_vetoes():
    total0 = 4  # src+map+agg+sink at 1 each
    pol = BacklogDrainPolicy(make_cfg(up_sustain=1, slot_budget=total0 + 1))
    sim = PolicySimulator(pol, chain_cluster())
    first = sim.step(constant(80_000))
    assert first.action == SCALE_UP and first.to_parallelism == 2
    # budget exhausted: the next recommendation must be a budget veto
    vetoes = []
    for _ in range(10):
        d = sim.step(constant(200_000))
        if d.action == VETO:
            vetoes.append(d)
    # cooldown vetoes may interleave; the budget veto must appear and
    # nothing may actuate past the budget
    assert any(d.reason == VETO_BUDGET for d in vetoes)
    assert all(d.reason in (VETO_BUDGET, "cooldown") for d in vetoes)
    assert sum(sim.cluster.parallelism.values()) == total0 + 1


def test_budget_veto_does_not_start_cooldown():
    """A slot-budget veto actuates nothing, so it must not refresh the
    cooldown clock: when load later drops, scale-down is measured from
    the last REAL action, not the last phantom veto."""
    pol = BacklogDrainPolicy(make_cfg(up_sustain=1, down_sustain=2,
                                      slot_budget=5))
    sim = PolicySimulator(pol, chain_cluster())
    # t=10: real scale-up (budget 5 allows agg 1->2), then mild
    # sustained overload keeps emitting slot_budget vetoes
    first = sim.step(constant(30_000))
    assert first.action == SCALE_UP
    vetoes = [sim.step(constant(30_000)) for _ in range(6)]  # t=20..70
    assert any(d.action == VETO and d.reason == VETO_BUDGET
               for d in vetoes)
    # load vanishes; the backlog drains and down_cooldown (60s) counted
    # from the REAL action at t=10 has long passed — the scale-down must
    # fire as soon as drain + sustain allow, with no cooldown veto from
    # the phantom budget "actions"
    tail = [sim.step(constant(1_000)) for _ in range(13)]  # t=80..200
    downs = [d for d in tail if d.action == SCALE_DOWN]
    assert downs, "budget vetoes blocked the post-drain scale-down"
    assert downs[0].t <= 130.0
    # pre-fix, the phantom action time turned post-drop recommendations
    # into cooldown vetoes; none may appear now
    assert not any(d.action == VETO and d.reason == "cooldown"
                   for d in tail)


def test_stale_rollup_vetoes_actions():
    """The actuation-refuses-stale-inputs contract: any recommendation on
    a rollup older than one evaluation interval is vetoed."""
    pol = BacklogDrainPolicy(make_cfg(up_sustain=1))
    sim = PolicySimulator(pol, chain_cluster(),
                          age_fn=lambda t: pol.cfg.interval_secs * 3)
    decisions = [sim.step(constant(80_000)) for _ in range(5)]
    assert all(d.action in (VETO, "hold") for d in decisions)
    stale = [d for d in decisions if d.action == VETO]
    assert stale and all(d.reason == VETO_STALE for d in stale)
    assert sim.cluster.parallelism["agg"] == 1  # never actuated


def test_hysteresis_band_holds():
    """Pressure between low_water and high_water: no action ever."""
    pol = BacklogDrainPolicy(make_cfg(up_sustain=1, down_sustain=1,
                                      down_cooldown_secs=0.0))
    sim = PolicySimulator(pol, chain_cluster(agg_p=2))
    # 24k into 2x10k capacity -> util 1.2 -> bp 0.4, inside [0.2, 0.7]
    for _ in range(10):
        d = sim.step(constant(24_000))
    assert all(x.action == "hold" for x in sim.ledger.decisions())
    assert sim.cluster.parallelism["agg"] == 2


def test_plan_pinned_operator_never_recommended():
    """StreamNode.max_parallelism pins are hard ceilings: recommending
    past them would checkpoint-stop the whole job for a rescale that
    update_parallelism silently clamps to a no-op — forever."""
    pol = BacklogDrainPolicy(make_cfg(up_sustain=1))
    hot = [{"operator_id": "src", "backpressure": 1.0, "watermark_lag": 0.0,
            "records_per_sec": 1e4, "age_secs": 0.0},
           {"operator_id": "agg", "backpressure": 0.0, "watermark_lag": 0.0,
            "records_per_sec": 1e4, "age_secs": 0.0}]
    for _ in range(5):
        d = pol.evaluate(EvalInput(
            now=10.0, rollups=hot, parallelism={"src": 1, "agg": 1},
            upstream={"src": [], "agg": ["src"]}, hard_max={"agg": 1}))
        assert d.action == "hold", d
    # same signals without the pin DO recommend
    d = pol.evaluate(EvalInput(
        now=10.0, rollups=hot, parallelism={"src": 1, "agg": 1},
        upstream={"src": [], "agg": ["src"]}))
    assert d.action == SCALE_UP and d.operator_id == "agg"


def test_operator_missing_from_rollup_is_not_calm():
    """A heartbeat-dead worker's operator vanishes from job_rollup while
    siblings keep it fresh — absence must never read as calm and allow
    a scale-down of the invisible (possibly overloaded) operator."""
    pol = BacklogDrainPolicy(make_cfg(up_sustain=1, down_sustain=1,
                                      down_cooldown_secs=0.0))
    partial = [{"operator_id": "src", "backpressure": 0.0,
                "watermark_lag": 0.0, "records_per_sec": 100.0,
                "age_secs": 0.0}]  # agg's worker stopped reporting
    for i in range(5):
        d = pol.evaluate(EvalInput(
            now=10.0 * (i + 1), rollups=partial,
            parallelism={"src": 1, "agg": 4},
            upstream={"src": [], "agg": ["src"]}))
        assert not (d.action == SCALE_DOWN and d.operator_id == "agg"), d


def test_starving_sibling_not_indicted_by_shared_lag():
    """Live rollups propagate watermark lag to EVERY branch behind a
    stalled shared upstream — a starving sibling (high queue_wait) must
    not be scaled up on that shared lag; only the true bottleneck is."""
    pol = BacklogDrainPolicy(make_cfg(up_sustain=1))
    rollups = [
        {"operator_id": "src", "backpressure": 1.0, "watermark_lag": 120.0,
         "queue_wait": 0.0, "records_per_sec": 1e4, "age_secs": 0.0},
        {"operator_id": "cold", "backpressure": 0.0,
         "watermark_lag": 120.0, "queue_wait": 2.0,  # waiting on input
         "records_per_sec": 100.0, "age_secs": 0.0},
        {"operator_id": "hot", "backpressure": 0.0, "watermark_lag": 120.0,
         "queue_wait": 0.0, "records_per_sec": 1e4, "age_secs": 0.0},
    ]
    d = pol.evaluate(EvalInput(
        now=10.0, rollups=rollups,
        parallelism={"src": 1, "cold": 1, "hot": 1},
        upstream={"src": [], "cold": ["src"], "hot": ["src"]}))
    # 'cold' sorts before 'hot' — only the starving discount keeps the
    # recommendation on the real bottleneck
    assert d.action == SCALE_UP and d.operator_id == "hot"


def test_partial_rollup_blocks_all_scale_downs():
    """When any operator is missing from the rollup (heartbeat-dead
    worker), no operator may scale down — the invisible one might be
    the hot one, and shrinking a sibling mid-incident doubles the harm."""
    pol = BacklogDrainPolicy(make_cfg(up_sustain=1, down_sustain=1,
                                      down_cooldown_secs=0.0))
    partial = [{"operator_id": "src", "backpressure": 0.0,
                "watermark_lag": 0.0, "records_per_sec": 100.0,
                "age_secs": 0.0},
               {"operator_id": "b", "backpressure": 0.0,
                "watermark_lag": 0.0, "records_per_sec": 100.0,
                "age_secs": 0.0}]  # operator "c" vanished
    for i in range(5):
        d = pol.evaluate(EvalInput(
            now=10.0 * (i + 1), rollups=partial,
            parallelism={"src": 1, "b": 4, "c": 2},
            upstream={"src": [], "b": ["src"], "c": ["b"]}))
        assert d.action != SCALE_DOWN, d
    # same signals with "c" visible and calm DO allow the scale-down
    full = partial + [{"operator_id": "c", "backpressure": 0.0,
                       "watermark_lag": 0.0, "records_per_sec": 100.0,
                       "age_secs": 0.0}]
    for i in range(3):
        d = pol.evaluate(EvalInput(
            now=100.0 + 10.0 * i, rollups=full,
            parallelism={"src": 1, "b": 4, "c": 2},
            upstream={"src": [], "b": ["src"], "c": ["b"]}))
    assert d.action == SCALE_DOWN


def test_empty_rollup_holds():
    pol = BacklogDrainPolicy(make_cfg())
    d = pol.evaluate(EvalInput(now=1.0, rollups=[], parallelism={"a": 1},
                               upstream={"a": []}))
    assert d.action == "hold" and d.reason == "no_rollup"


def test_replay_open_loop():
    pol = BacklogDrainPolicy(make_cfg(up_sustain=1))
    hot = [{"operator_id": "src", "backpressure": 1.0, "watermark_lag": 0.0,
            "records_per_sec": 1000.0, "age_secs": 0.0},
           {"operator_id": "agg", "backpressure": 0.0, "watermark_lag": 30.0,
            "records_per_sec": 1000.0, "age_secs": 0.0}]
    out = replay(pol, [hot, hot], parallelism={"src": 1, "agg": 1},
                 upstream={"src": [], "agg": ["src"]})
    assert out[0].action == SCALE_UP and out[0].operator_id == "agg"


def test_policy_config_merge():
    cfg = PolicyConfig()
    new = cfg.merged({"high_water": 0.5, "per_op": {"x": {"max": 4}}})
    assert new.high_water == 0.5 and new.bounds("x") == (1, 4)
    assert cfg.high_water == 0.7  # original untouched
    with pytest.raises(KeyError):
        cfg.merged({"not_a_knob": 1})
    # values are coerced: a stringly-typed REST update must either
    # become the right type or fail the PUT — never poison evaluate()
    assert cfg.merged({"high_water": "0.9"}).high_water == 0.9
    assert cfg.merged({"up_sustain": "3"}).up_sustain == 3
    assert cfg.merged({"slot_budget": None}).slot_budget is None
    with pytest.raises(ValueError):
        cfg.merged({"high_water": "hot"})
    with pytest.raises(ValueError):
        cfg.merged({"per_op": {"x": 4}})
    with pytest.raises(ValueError):
        # a typo'd bound key must fail the PUT, not silently unpin
        cfg.merged({"per_op": {"x": {"mx": 1}}})
    # range checks: knobs that would break the loop itself are refused
    for bad in ({"interval_secs": 0}, {"interval_secs": float("nan")},
                {"high_water": 0.1},            # inverts the band
                {"high_water": 7},              # pressure is [0,1]
                {"up_sustain": 0}, {"max_step_factor": 1.0},
                {"max_parallelism": 0}, {"slot_budget": 0},
                {"lag_warn_secs": 100.0},       # above lag_high
                {"per_op": {"x": {"min": 3, "max": 2}}}):
        with pytest.raises(ValueError):
            cfg.merged(bad)


def test_autoscaler_spec_persists_across_controller_restart(tmp_path):
    """A durable controller resumes the autoscaler with the job: the
    stored enabled flag + policy come back after a restart."""
    import json as _json

    from arroyo_tpu.controller.controller import ControllerServer, Job
    from arroyo_tpu.controller.scheduler import InProcessScheduler
    from arroyo_tpu.controller.store import ControllerStore

    db = str(tmp_path / "ctrl.db")

    async def first_life():
        ctrl = ControllerServer(InProcessScheduler(), db_path=db)
        ctrl.jobs["jp"] = Job("jp", _tiny_program(), "file:///tmp/x", 1)
        ctrl.store.upsert_job("jp", b"x", "file:///tmp/x", 1, "Running")
        ctrl._attach_autoscaler("jp")
        scaler = ctrl.autoscalers["jp"]
        scaler.policy.cfg = scaler.policy.cfg.merged({"high_water": 0.42})
        scaler.set_enabled(True)
        ctrl.persist_autoscaler("jp")
        scaler.stop()
        ctrl.store.close()

    asyncio.run(first_life())

    # the stored row carries the spec...
    store = ControllerStore(db)
    (row,) = store.resumable()
    store.close()
    spec = _json.loads(row.autoscale)
    assert spec["enabled"] and spec["policy"]["high_water"] == 0.42

    async def second_life():
        ctrl = ControllerServer(InProcessScheduler(), db_path=db)
        ctrl.jobs["jp"] = Job("jp", _tiny_program(), "file:///tmp/x", 1)
        ctrl._attach_autoscaler("jp")
        # ...and the resume path re-arms the loop from it
        ctrl._restore_autoscaler("jp", row.autoscale)
        scaler = ctrl.autoscalers["jp"]
        out = (scaler.enabled, scaler.running,
               scaler.policy.cfg.high_water)
        scaler.stop()
        ctrl.store.close()
        return out

    enabled, running, hw = asyncio.run(second_life())
    assert enabled and running and hw == 0.42

    # a persisted enabled:false must override a default-on attach: an
    # explicitly disabled autoscaler stays off across restarts
    import json as _json2

    off_spec = _json2.dumps({"enabled": False, "policy": None})

    async def third_life(monkey_default_on):
        ctrl = ControllerServer(InProcessScheduler(), db_path=db)
        ctrl.jobs["jp"] = Job("jp", _tiny_program(), "file:///tmp/x", 1)
        ctrl._attach_autoscaler("jp")
        if monkey_default_on:  # simulate ARROYO_AUTOSCALE_DEFAULT=1
            ctrl.autoscalers["jp"].set_enabled(True)
        ctrl._restore_autoscaler("jp", off_spec)
        scaler = ctrl.autoscalers["jp"]
        out = (scaler.enabled, scaler.running)
        scaler.stop()
        ctrl.store.close()
        return out

    assert asyncio.run(third_life(True)) == (False, False)

    # an invalid stored policy (e.g. interval 0, which would busy-spin
    # the controller) falls back to defaults but STILL applies the
    # stored enabled flag
    bad_spec = _json2.dumps({
        "enabled": False,
        "policy": dict(PolicyConfig().to_json(), interval_secs=0)})

    async def fourth_life():
        ctrl = ControllerServer(InProcessScheduler(), db_path=db)
        ctrl.jobs["jp"] = Job("jp", _tiny_program(), "file:///tmp/x", 1)
        ctrl._attach_autoscaler("jp")
        ctrl.autoscalers["jp"].set_enabled(True)  # default-on analog
        ctrl._restore_autoscaler("jp", bad_spec)
        scaler = ctrl.autoscalers["jp"]
        out = (scaler.enabled, scaler.policy.cfg.interval_secs)
        scaler.stop()
        ctrl.store.close()
        return out

    enabled, interval = asyncio.run(fourth_life())
    assert enabled is False and interval > 0


# ---------------------------------------------------------------------------
# supervisor unit tests (controller double)
# ---------------------------------------------------------------------------


def _tiny_program():
    return (Stream.source("impulse", {"event_rate": 0.0,
                                      "message_count": 10})
            .sink("blackhole", {}))


class _CtrlDouble:
    """Just enough controller for JobAutoscaler.evaluate_once."""

    def __init__(self, rollups):
        from arroyo_tpu.controller.controller import Job

        self.rollups = rollups
        self.jobs = {"j1": Job("j1", _tiny_program(), "file:///tmp/x", 1)}
        self.autoscalers = {}
        self.rescales = []
        self.fail_rescale = False
        job = self.jobs["j1"]
        from arroyo_tpu.controller.state_machine import JobState

        job.fsm.transition(JobState.COMPILING)
        job.fsm.transition(JobState.SCHEDULING)
        job.fsm.transition(JobState.RUNNING)

    def job_rollup(self, job_id):
        return self.rollups

    async def rescale_job(self, job_id, overrides):
        if self.fail_rescale:
            raise TimeoutError("stop-checkpoint incomplete")
        self.rescales.append((job_id, overrides))


def _hot_rollups(op_src, op_sink, age=0.0):
    return [{"operator_id": op_src, "backpressure": 1.0,
             "watermark_lag": 0.0, "records_per_sec": 1e4,
             "age_secs": age},
            {"operator_id": op_sink, "backpressure": 0.0,
             "watermark_lag": 0.0, "records_per_sec": 1e4,
             "age_secs": age}]


def _ops(ctrl):
    return [n.operator_id for n in ctrl.jobs["j1"].program.nodes()]


def test_supervisor_actuates_and_records():
    async def scenario():
        ctrl = _CtrlDouble([])
        src, sink = _ops(ctrl)
        ctrl.rollups = _hot_rollups(src, sink)
        a = JobAutoscaler(ctrl, "j1", policy=BacklogDrainPolicy(
            make_cfg(up_sustain=1, interval_secs=0.5,
                     per_op={sink: {"min": 1, "max": 4}},
                     max_parallelism=1)))
        d = await a.evaluate_once(ctrl.jobs["j1"])
        assert d.action == SCALE_UP and d.actuated
        assert ctrl.rescales == [("j1", {sink: 2})]
        assert a.ledger.actuations == 1
        assert a.status()["decisions"][-1]["actuated"] is True
        return True

    assert asyncio.run(scenario())


def test_supervisor_vetoes_stale_rollup():
    """Satellite contract: rollups older than one evaluation interval
    must veto the actuation and count in the ledger."""
    async def scenario():
        ctrl = _CtrlDouble([])
        src, sink = _ops(ctrl)
        ctrl.rollups = _hot_rollups(src, sink, age=10.0)
        a = JobAutoscaler(ctrl, "j1", policy=BacklogDrainPolicy(
            make_cfg(up_sustain=1, interval_secs=0.5,
                     per_op={sink: {"min": 1, "max": 4}},
                     max_parallelism=1)))
        d = await a.evaluate_once(ctrl.jobs["j1"])
        assert d.action == VETO and d.reason == VETO_STALE
        assert ctrl.rescales == []
        assert a.ledger.vetoes == 1
        return True

    assert asyncio.run(scenario())


def test_supervisor_records_actuation_failure():
    async def scenario():
        ctrl = _CtrlDouble([])
        src, sink = _ops(ctrl)
        ctrl.rollups = _hot_rollups(src, sink)
        ctrl.fail_rescale = True
        a = JobAutoscaler(ctrl, "j1", policy=BacklogDrainPolicy(
            make_cfg(up_sustain=1, interval_secs=0.5,
                     per_op={sink: {"min": 1, "max": 4}},
                     max_parallelism=1)))
        d = await a.evaluate_once(ctrl.jobs["j1"])
        assert d.action == SCALE_UP and not d.actuated
        assert "stop-checkpoint" in d.error
        assert a.ledger.actuations == 0 and a.ledger.vetoes == 1
        return True

    assert asyncio.run(scenario())


# ---------------------------------------------------------------------------
# REST surface
# ---------------------------------------------------------------------------


def test_autoscaler_rest_endpoints(tmp_path):
    from arroyo_tpu.api.rest import ApiServer
    from arroyo_tpu.controller.controller import ControllerServer, Job
    from arroyo_tpu.controller.scheduler import InProcessScheduler

    async def scenario():
        ctrl = ControllerServer(InProcessScheduler())
        await ctrl.start()
        api = ApiServer(ctrl)
        port = await api.start()
        # a registered job the REST layer can address, without workers
        ctrl.jobs["j1"] = Job("j1", _tiny_program(),
                              f"file://{tmp_path}/ckpt", 1)
        base = f"http://127.0.0.1:{port}"
        try:
            async with httpx.AsyncClient(base_url=base, timeout=10) as c:
                r = await c.get("/v1/jobs/nope/autoscaler")
                assert r.status_code == 404
                r = await c.get("/v1/jobs/j1/autoscaler")
                assert r.status_code == 200
                body = r.json()
                assert body["enabled"] is False and body["decisions"] == []
                # enable + merge a policy knob
                r = await c.put("/v1/jobs/j1/autoscaler",
                                json={"enabled": True,
                                      "policy": {"high_water": 0.5}})
                assert r.status_code == 200
                body = r.json()
                assert body["enabled"] and body["running"]
                assert body["policy"]["high_water"] == 0.5
                r = await c.put("/v1/jobs/j1/autoscaler",
                                json={"policy": {"bogus": 1}})
                assert r.status_code == 422
                # a rejected PUT on a scaler-less job must not leave a
                # freshly attached loop (or persisted spec) behind
                ctrl.autoscalers.pop("j1").stop()
                r = await c.put("/v1/jobs/j1/autoscaler",
                                json={"enabled": True,
                                      "policy": {"interval_secs": 0}})
                assert r.status_code == 422
                assert "j1" not in ctrl.autoscalers
                r = await c.put("/v1/jobs/j1/autoscaler",
                                json={"enabled": False})
                assert r.json()["enabled"] is False
        finally:
            await api.stop()
            await ctrl.stop()
        return True

    assert asyncio.run(scenario())


def test_global_escape_hatch_disables(tmp_path, monkeypatch):
    """ARROYO_AUTOSCALE=0: no loops attach, and the REST PUT refuses."""
    from arroyo_tpu.api.rest import ApiServer
    from arroyo_tpu.controller.controller import ControllerServer, Job
    from arroyo_tpu.controller.scheduler import InProcessScheduler

    monkeypatch.setenv("ARROYO_AUTOSCALE", "0")
    cfg_mod.reset_config()

    async def scenario():
        ctrl = ControllerServer(InProcessScheduler())
        await ctrl.start()
        api = ApiServer(ctrl)
        port = await api.start()
        ctrl.jobs["j1"] = Job("j1", _tiny_program(),
                              f"file://{tmp_path}/ckpt", 1)
        ctrl._attach_autoscaler("j1")  # what submit_job would do
        base = f"http://127.0.0.1:{port}"
        try:
            assert ctrl.autoscalers == {}
            async with httpx.AsyncClient(base_url=base, timeout=10) as c:
                r = await c.get("/v1/jobs/j1/autoscaler")
                assert r.status_code == 200
                assert r.json()["global_enabled"] is False
                r = await c.put("/v1/jobs/j1/autoscaler",
                                json={"enabled": True})
                assert r.status_code == 409
        finally:
            await api.stop()
            await ctrl.stop()
        return True

    try:
        assert asyncio.run(scenario())
    finally:
        monkeypatch.delenv("ARROYO_AUTOSCALE")
        cfg_mod.reset_config()


# ---------------------------------------------------------------------------
# checkpoint retention (satellite)
# ---------------------------------------------------------------------------


def test_prune_checkpoints_to_retention(tmp_path, monkeypatch):
    """cleanup_before prunes to the configured retention after a restore
    point — the storage directory is the proof."""
    from arroyo_tpu.controller.controller import ControllerServer, Job
    from arroyo_tpu.controller.scheduler import InProcessScheduler
    from arroyo_tpu.state.backend import ParquetBackend

    monkeypatch.setenv("CHECKPOINT_RETENTION", "3")
    cfg_mod.reset_config()
    url = f"file://{tmp_path}/ckpt"
    backend = ParquetBackend.for_url(url)
    for epoch in range(1, 6):
        backend.storage.put(
            f"jr/checkpoints/checkpoint-{epoch:07d}/metadata.json",
            json.dumps({"complete": True, "epoch": epoch}).encode())

    async def scenario():
        ctrl = ControllerServer(InProcessScheduler())
        job = Job("jr", _tiny_program(), url, 1)
        job.last_successful_epoch = 5
        await ctrl._prune_checkpoints(job)
        return job.min_epoch

    try:
        min_epoch = asyncio.run(scenario())
    finally:
        monkeypatch.delenv("CHECKPOINT_RETENTION")
        cfg_mod.reset_config()
    assert min_epoch == 3
    kept = sorted(p.name for p in (tmp_path / "ckpt" / "jr"
                                   / "checkpoints").iterdir())
    assert kept == ["checkpoint-0000003", "checkpoint-0000004",
                    "checkpoint-0000005"]


@pytest.mark.slow
def test_cluster_checkpoints_pruned_live(tmp_path, monkeypatch):
    """End-to-end: periodic checkpoints on a real cluster leave at most
    ``checkpoint_retention`` completed epochs in storage."""
    from arroyo_tpu.controller.controller import ControllerServer
    from arroyo_tpu.controller.scheduler import InProcessScheduler
    from arroyo_tpu.controller.state_machine import JobState

    monkeypatch.setenv("CHECKPOINT_RETENTION", "2")
    monkeypatch.setenv("CHECKPOINT_INTERVAL_SECS", "0.3")
    cfg_mod.reset_config()
    out_path = tmp_path / "out.jsonl"

    async def scenario():
        ctrl = ControllerServer(InProcessScheduler())
        await ctrl.start()
        prog = (
            Stream.source("impulse", {"event_rate": 12_000.0,
                                      "message_count": 30_000,
                                      "event_time_interval_micros": 1000,
                                      "batch_size": 256})
            .watermark(max_lateness_micros=0)
            .key_by("subtask_index")
            .tumbling_aggregate(100 * 1000,
                                [AggSpec(AggKind.COUNT, None, "cnt")])
            .sink("single_file", {"path": str(out_path)})
        )
        job_id = await ctrl.submit_job(
            prog, checkpoint_url=f"file://{tmp_path}/ckpt")
        state = await ctrl.wait_for_state(job_id, JobState.FINISHED,
                                          timeout=60)
        job = ctrl.jobs[job_id]
        # deterministic final prune: the LAST checkpoint's finalize can
        # still be between its metadata write and its retention pass
        # when FINISHED lands — on a loaded box, tearing down right
        # here cancelled that in-flight prune and left retention+1
        # complete epochs on disk (the long-standing straggler).
        # _prune_checkpoints is idempotent, so settling it explicitly
        # removes the race without widening the run; the direct
        # cleanup_before covers the other half (finalize cancelled
        # AFTER advancing min_epoch but before the storage pass, where
        # _prune_checkpoints would early-return on the stale marker).
        await ctrl._prune_checkpoints(job)
        from arroyo_tpu.state.backend import ParquetBackend

        backend = ParquetBackend.for_url(job.checkpoint_url)
        await asyncio.get_running_loop().run_in_executor(
            None, backend.cleanup_before, job_id, job.min_epoch)
        await ctrl.scheduler.stop_workers(job_id)
        await ctrl.stop()
        return state, job.last_successful_epoch, job_id

    try:
        state, last_epoch, job_id = asyncio.run(scenario())
    finally:
        monkeypatch.delenv("CHECKPOINT_RETENTION")
        monkeypatch.delenv("CHECKPOINT_INTERVAL_SECS")
        cfg_mod.reset_config()
    assert state == JobState.FINISHED
    assert last_epoch and last_epoch > 2, "not enough epochs to prune"
    ckpt_dir = tmp_path / "ckpt" / job_id / "checkpoints"
    complete = [p for p in ckpt_dir.iterdir()
                if (p / "metadata.json").exists()
                and json.loads((p / "metadata.json").read_text())
                .get("complete")]
    assert len(complete) <= 2, sorted(p.name for p in complete)


# ---------------------------------------------------------------------------
# live e2e: injected load -> autoscaler -> rescale_job -> correct output
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_autoscaler_live_rescale_e2e(tmp_path, monkeypatch):
    """An impulse load ramp drives the autoscaler through the REAL
    controller: the policy sees the job's rollups, actuates a live
    ``rescale_job`` on the bottleneck aggregate, the job keeps producing
    exactly-once output across the rescale, and the decision ledger at
    ``GET /v1/jobs/{id}/autoscaler`` records the actuation."""
    from arroyo_tpu.api.rest import ApiServer
    from arroyo_tpu.controller.controller import ControllerServer
    from arroyo_tpu.controller.scheduler import InProcessScheduler
    from arroyo_tpu.controller.state_machine import JobState

    monkeypatch.setenv("HEARTBEAT_INTERVAL_SECS", "0.2")
    cfg_mod.reset_config()
    out_path = tmp_path / "out.jsonl"
    N = 250_000  # flood: long enough that the rescale lands mid-stream

    async def scenario():
        ctrl = ControllerServer(InProcessScheduler())
        await ctrl.start()
        api = ApiServer(ctrl)
        port = await api.start()
        prog = (
            Stream.source("impulse", {"event_rate": 0.0,  # flood
                                      "message_count": N,
                                      "event_time_interval_micros": 1000,
                                      "batch_size": 256}, parallelism=1)
            .watermark(max_lateness_micros=0)
            .map(lambda c: {"counter": c["counter"],
                            "bucket": c["counter"] % 6}, name="b")
            .key_by("bucket")
            .tumbling_aggregate(
                500 * 1000, [AggSpec(AggKind.COUNT, None, "cnt")],
                parallelism=1)
            .sink("single_file", {"path": str(out_path)}, parallelism=1)
        )
        agg_id = next(n.operator_id for n in prog.nodes()
                      if "aggregator" in n.operator_id)
        job_id = await ctrl.submit_job(
            prog, checkpoint_url=f"file://{tmp_path}/ckpt", n_workers=1)
        scaler = ctrl.autoscalers[job_id]
        # aggressive test policy: every operator pinned except the
        # aggregate, zero trigger threshold (the first rollup IS the
        # signal — signal discipline itself is the simulator suite's
        # job), sustain 1, long cooldown so exactly one actuation fires
        scaler.policy = BacklogDrainPolicy(PolicyConfig(
            interval_secs=0.3, high_water=0.0, up_sustain=1,
            up_cooldown_secs=600.0, down_cooldown_secs=600.0,
            max_parallelism=1, per_op={agg_id: {"min": 1, "max": 2}}))
        scaler.set_enabled(True)
        try:
            await ctrl.wait_for_state(job_id, JobState.RUNNING, timeout=30)
            # wait for the actuation (or the job finishing under us,
            # which the assertion below will flag)
            for _ in range(600):
                if scaler.ledger.actuations > 0:
                    break
                if ctrl.jobs[job_id].fsm.state.terminal:
                    break
                await asyncio.sleep(0.05)
            state = await ctrl.wait_for_state(job_id, JobState.FINISHED,
                                              timeout=120)
            async with httpx.AsyncClient(
                    base_url=f"http://127.0.0.1:{port}", timeout=10) as c:
                r = await c.get(f"/v1/jobs/{job_id}/autoscaler")
                rest_body = r.json()
            return (state, scaler.ledger.actuations,
                    prog.node(agg_id).parallelism, rest_body)
        finally:
            await ctrl.scheduler.stop_workers(job_id)
            await api.stop()
            await ctrl.stop()

    try:
        state, actuations, agg_p, rest_body = asyncio.run(scenario())
    finally:
        monkeypatch.delenv("HEARTBEAT_INTERVAL_SECS")
        cfg_mod.reset_config()

    assert state == JobState.FINISHED
    assert actuations >= 1, "autoscaler never actuated a live rescale"
    assert agg_p == 2  # the bottleneck operator scaled, nothing else
    acted = rest_body["actuated"]
    assert acted and acted[0]["action"] == "scale_up"
    assert acted[0]["actuated"] is True
    assert "aggregator" in acted[0]["operator_id"]
    rows = [json.loads(line) for line in open(out_path)]
    assert sum(r["cnt"] for r in rows) == N  # exactly-once across rescale
