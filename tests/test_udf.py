"""SQL UDF / UDAF registration and execution (the reference's Rust-UDF
registration arroyo-sql/src/lib.rs:196-290 + worker execution
operators/mod.rs:347-494), including BASELINE.md config #5: session-window
aggregation with a UDAF over a Kafka source with checkpoint/restore."""

import asyncio
import json

import numpy as np
import pytest

from arroyo_tpu import Batch
from arroyo_tpu.connectors.kafka import InMemoryKafkaBroker
from arroyo_tpu.connectors.memory import clear_sink, sink_output
from arroyo_tpu.engine.engine import Engine, LocalRunner
from arroyo_tpu.sql import (
    SchemaProvider,
    SqlPlanError,
    plan_sql,
    unregister_udfs,
)
from arroyo_tpu.types import StopMode

SEC = 1_000_000


@pytest.fixture(autouse=True)
def _clean_udfs():
    yield
    unregister_udfs()


def run_sql(sql, provider=None):
    clear_sink("results")
    prog = plan_sql(sql, provider)
    LocalRunner(prog).run()
    outs = sink_output("results")
    return Batch.concat(outs) if outs else None


def events_table(p, n=200):
    rng = np.random.default_rng(3)
    ts = np.sort(rng.integers(0, 3 * SEC, n)).astype(np.int64)
    p.add_memory_table("events", {"k": "i", "v": "f", "name": "s"}, [
        Batch(ts, {"k": rng.integers(0, 4, n).astype(np.int64),
                   "v": rng.random(n).astype(np.float64) * 100,
                   "name": np.array([f"u{i % 3}" for i in range(n)],
                                    dtype=object)})])
    return p


def test_scalar_udf_in_projection():
    p = SchemaProvider()
    p.register_udf("add_tax", lambda v: v * 1.2)
    p.register_udf("shout", lambda s: np.array(
        [x.upper() + "!" if x is not None else None for x in s],
        dtype=object))
    events_table(p)
    out = run_sql("SELECT add_tax(v) as taxed, shout(name) as n2, v "
                  "FROM events WHERE add_tax(v) > 60", p)
    assert out is not None and len(out) > 0
    np.testing.assert_allclose(out.columns["taxed"],
                               np.asarray(out.columns["v"]) * 1.2,
                               rtol=1e-6)
    assert np.all(out.columns["taxed"] > 60)
    assert set(np.unique(list(out.columns["n2"]))) <= {"U0!", "U1!", "U2!"}


def test_udaf_tumbling_window_matches_numpy():
    p = SchemaProvider()
    p.register_udaf("median", np.median)
    p.register_udaf("p90", lambda v: float(np.percentile(v, 90)))
    events_table(p)
    out = run_sql(
        "SELECT k, median(v) as med, p90(v) as p90v, count(*) as cnt "
        "FROM events GROUP BY k, tumble(interval '1 second')", p)
    assert out is not None
    # oracle: recompute per (key, window) from the source batch
    src = events_table(SchemaProvider()).get("events").config["batches"][0]
    groups = {}
    for t, k, v in zip(src.timestamp.tolist(), src.columns["k"].tolist(),
                       src.columns["v"].tolist()):
        groups.setdefault((k, (t // SEC + 1) * SEC), []).append(v)
    for i in range(len(out)):
        key = (int(out.columns["k"][i]), int(out.columns["window_end"][i]))
        vals = np.asarray(groups[key])
        assert out.columns["cnt"][i] == len(vals)
        assert out.columns["med"][i] == pytest.approx(np.median(vals))
        assert out.columns["p90v"][i] == pytest.approx(
            np.percentile(vals, 90))


def test_udaf_without_window_rejected():
    p = SchemaProvider()
    p.register_udaf("median", np.median)
    events_table(p)
    with pytest.raises(SqlPlanError, match="requires a window"):
        plan_sql("CREATE TABLE out WITH (connector='memory', "
                 "name='results'); INSERT INTO out "
                 "SELECT k, median(v) FROM events GROUP BY k", p)


def test_udf_cannot_shadow_builtin():
    p = SchemaProvider()
    with pytest.raises(ValueError, match="shadow"):
        p.register_udf("upper", lambda s: s)
    with pytest.raises(ValueError, match="shadow"):
        p.register_udaf("sum", np.sum)


def test_baseline5_session_udaf_kafka_checkpoint(tmp_path):
    """BASELINE.md config #5: session-window aggregation with a UDAF over
    a Kafka source, with a checkpoint + restore in the middle of an OPEN
    session — the buffered session state must survive the restore and the
    session must close with every value from both runs."""
    InMemoryKafkaBroker.reset("u5")
    broker = InMemoryKafkaBroker.get("u5")
    broker.create_topic("sess", partitions=1)

    # run-1 events: key 1 session [0.0s, 1.0s], key 2 single at 0.2s
    run1 = [(1, 10.0, 0), (1, 30.0, 500_000), (2, 5.0, 200_000),
            (1, 20.0, 1_000_000)]
    for k, v, ts in run1:
        broker.produce("sess", json.dumps(
            {"k": k, "v": v, "ts": ts * 1000}).encode(), partition=0)

    p = SchemaProvider()
    p.register_udaf("median", np.median)
    sql = """
    CREATE TABLE ev (
      k BIGINT, v DOUBLE, ts BIGINT,
      event_time TIMESTAMP GENERATED ALWAYS AS
        (CAST(from_unixtime(ts) as TIMESTAMP))
    ) WITH (
      connector = 'kafka', bootstrap_servers = 'memory://u5',
      topic = 'sess', type = 'source', format = 'json',
      event_time_field = 'event_time', batch_size = '2'
    );
    CREATE TABLE out WITH (connector = 'memory', name = 'results');
    INSERT INTO out
    SELECT k, median(v) as med, count(*) as cnt,
           session(INTERVAL '1' SECOND) as window
    FROM ev GROUP BY 1, 4
    """
    url = f"file://{tmp_path}/ckpt"
    clear_sink("results")

    async def run_phase(restore, epoch, settle_secs):
        prog = plan_sql(sql, p)
        eng = Engine.for_local(prog, "udaf-job", checkpoint_url=url,
                               restore_epoch=restore)
        running = eng.start()
        await asyncio.sleep(settle_secs)  # let the source drain the topic
        await running.checkpoint(epoch)
        assert await running.wait_for_checkpoint(epoch)
        await running.stop(StopMode.IMMEDIATE)
        try:
            await running.join()
        except RuntimeError:
            pass

    # phase 1: consume the run-1 events, checkpoint with the session OPEN
    # (max run-1 event time is 1.0s and lateness is 1s, so the watermark
    # cannot reach any session end — nothing may fire before the restore)
    asyncio.run(run_phase(None, 1, 0.6))
    assert not sink_output("results"), "session fired before its gap closed"

    # run-2 events: key 1's session EXTENDS at 1.4s (gap 1s from 1.0s),
    # then a far event advances the watermark past the session end
    run2 = [(1, 40.0, 1_400_000), (1, 99.0, 10_000_000)]
    for k, v, ts in run2:
        broker.produce("sess", json.dumps(
            {"k": k, "v": v, "ts": ts * 1000}).encode(), partition=0)

    asyncio.run(run_phase(1, 2, 0.8))
    out = Batch.concat(sink_output("results"))
    rows = {}
    for i in range(len(out)):
        rows[(int(out.columns["k"][i]),
              int(out.columns["window_start"][i]))] = (
            int(out.columns["cnt"][i]), float(out.columns["med"][i]))
    # key 1 session [0, 2.4s): all four values, including the three
    # buffered BEFORE the checkpoint -> median(10, 20, 30, 40) = 25
    assert rows[(1, 0)] == (4, 25.0)
    # key 2 session [0.2s, 1.2s)
    assert rows[(2, 200_000)] == (1, 5.0)


def test_udaf_distinct_and_arity_rejected():
    p = SchemaProvider()
    p.register_udaf("median", np.median)
    events_table(p)
    base = ("CREATE TABLE out WITH (connector='memory', name='results');"
            "INSERT INTO out ")
    with pytest.raises(SqlPlanError, match="DISTINCT"):
        plan_sql(base + "SELECT k, median(DISTINCT v) FROM events "
                 "GROUP BY k, tumble(interval '1 second')", p)
    with pytest.raises(SqlPlanError, match="exactly one column"):
        plan_sql(base + "SELECT k, median(v, k) FROM events "
                 "GROUP BY k, tumble(interval '1 second')", p)


# ---------------------------------------------------------------------------
# vectorized UDAF channels (ops/udaf.py, PR 19): numeric UDAFs compile
# onto mergeable sum/nnz/min/max/sumsq partials instead of the
# per-segment host loop
# ---------------------------------------------------------------------------


@pytest.fixture
def _fresh_verdicts():
    from arroyo_tpu.ops import udaf

    saved = dict(udaf._verdicts)
    udaf._verdicts.clear()
    yield
    udaf._verdicts.clear()
    udaf._verdicts.update(saved)


@pytest.mark.parametrize("fn,expect", [
    (np.sum, "sum"),
    (np.mean, "mean"),
    (np.min, "min"),
    (np.max, "max"),
    (np.ptp, "ptp"),
    (np.var, "var_pop"),
    (np.std, "std_pop"),
    (lambda v: np.var(v, ddof=1), "var_samp"),
    (lambda v: np.std(v, ddof=1), "std_samp"),
    (len, "count"),
    (lambda v: float(v.sum() / len(v)), "mean"),
    (np.median, None),
    (lambda v: float(np.percentile(v, 90)), None),
    (lambda v: "not a number", None),
])
def test_udaf_probe_classification(_fresh_verdicts, fn, expect):
    """Behavioral probing against the partial algebra: extensional
    equality on the dyadic probe vectors decides the plan, so np.mean
    and a hand-rolled mean both compile; order statistics and
    non-numeric returns stay on the host loop."""
    from arroyo_tpu.ops.udaf import udaf_plan

    plan = udaf_plan(fn)
    if expect is None:
        assert plan is None
    else:
        assert plan is not None and plan.name == expect
        assert "nnz" in plan.channels


def test_udaf_verdict_sticky_and_knob(_fresh_verdicts, monkeypatch):
    from arroyo_tpu.ops import udaf

    calls = []

    def counting_mean(v):
        calls.append(1)
        return np.mean(v)

    assert udaf.udaf_plan(counting_mean).name == "mean"
    probes = len(calls)
    assert udaf.udaf_plan(counting_mean).name == "mean"
    assert len(calls) == probes, "verdict must be sticky per fn object"

    monkeypatch.setenv("ARROYO_UDAF_CHANNELS", "off")
    udaf._verdicts.clear()
    assert udaf.udaf_plan(np.mean) is None, \
        "channels off: every UDAF takes the counted host loop"


def test_segment_udaf_channel_matches_host_loop(rng, _fresh_verdicts,
                                                monkeypatch):
    """segment_aggregate parity: the channel path and the per-segment
    host loop agree to float tolerance on fuzzed segments with nulls,
    and all-null segments emit NaN on both."""
    from arroyo_tpu.graph.logical import AggKind, AggSpec as LAggSpec
    from arroyo_tpu.ops.segment import segment_aggregate

    n = 4000
    kh = rng.integers(0, 60, n).astype(np.uint64)
    ts = np.sort(rng.integers(0, 10 * SEC, n)).astype(np.int64)
    vals = rng.random(n) * 100 - 50
    vals[rng.random(n) < 0.1] = np.nan
    vals[kh == 7] = np.nan  # one all-null key
    fns = [np.mean, np.var, lambda v: np.std(v, ddof=1), np.sum]
    aggs = tuple(
        LAggSpec(AggKind.UDAF, "v", f"o{i}", fn=fn)
        for i, fn in enumerate(fns))

    uniq_c, cols_c, _t, _n, vc_c = segment_aggregate(
        kh, ts, {"v": vals}, aggs)
    monkeypatch.setenv("ARROYO_UDAF_CHANNELS", "off")
    uniq_h, cols_h, _t, _n, vc_h = segment_aggregate(
        kh, ts, {"v": vals}, aggs)

    np.testing.assert_array_equal(uniq_c, uniq_h)
    for i in range(len(fns)):
        np.testing.assert_allclose(
            cols_c[f"o{i}"], cols_h[f"o{i}"], rtol=1e-9, atol=1e-9,
            equal_nan=True)
        np.testing.assert_array_equal(vc_c[f"o{i}"], vc_h[f"o{i}"])
    i7 = np.searchsorted(uniq_c, 7)
    assert np.isnan(cols_c["o0"][i7]), "all-null segment must emit NaN"


def test_udaf_channel_counters_split(rng, _fresh_verdicts):
    """The sticky fallback is COUNTED: channel-compiled rows on
    udaf_channel_rows, host-loop rows on udaf_host_rows."""
    from arroyo_tpu.graph.logical import AggKind, AggSpec as LAggSpec
    from arroyo_tpu.obs import perf
    from arroyo_tpu.ops.segment import segment_aggregate

    n = 512
    kh = rng.integers(0, 8, n).astype(np.uint64)
    ts = np.sort(rng.integers(0, SEC, n)).astype(np.int64)
    vals = rng.random(n)
    c0 = perf.counter("udaf_channel_rows")
    h0 = perf.counter("udaf_host_rows")
    segment_aggregate(kh, ts, {"v": vals}, (
        LAggSpec(AggKind.UDAF, "v", "m", fn=np.mean),
        LAggSpec(AggKind.UDAF, "v", "p", fn=lambda v: float(
            np.percentile(v, 90)))))
    assert perf.counter("udaf_channel_rows") - c0 == n
    assert perf.counter("udaf_host_rows") - h0 == n


def test_planner_compiles_udaf_to_binned_partials(_fresh_verdicts):
    """A decomposable numeric UDAF on a tumbling window plans onto the
    BINNED aggregator (hidden partial aggs + arithmetic combine) — the
    buffered generic window operator never materializes — and the
    output matches a per-window numpy oracle."""
    from arroyo_tpu.graph.logical import OpKind

    p = SchemaProvider()
    p.register_udaf("my_var", np.var)
    p.register_udaf("my_mean", lambda v: v.mean())
    events_table(p)
    sql = ("CREATE TABLE out WITH (connector='memory', name='results');"
           "INSERT INTO out SELECT k, my_var(v) as vv, my_mean(v) as mv, "
           "count(*) as cnt FROM events "
           "GROUP BY k, tumble(interval '1 second')")
    prog = plan_sql(sql, p)
    kinds = [prog.node(op).operator.kind for op in prog.graph.nodes]
    assert OpKind.TUMBLING_WINDOW_AGGREGATOR in kinds
    assert OpKind.WINDOW not in kinds, \
        "decomposable UDAFs must not force the buffered generic path"

    clear_sink("results")
    LocalRunner(prog).run()
    out = Batch.concat(sink_output("results"))
    src = events_table(SchemaProvider()).get("events").config["batches"][0]
    groups = {}
    for t, k, v in zip(src.timestamp.tolist(), src.columns["k"].tolist(),
                       src.columns["v"].tolist()):
        groups.setdefault((k, (t // SEC + 1) * SEC), []).append(v)
    assert len(out) == len(groups)
    for i in range(len(out)):
        key = (int(out.columns["k"][i]), int(out.columns["window_end"][i]))
        vals = np.asarray(groups[key])
        assert out.columns["cnt"][i] == len(vals)
        assert out.columns["vv"][i] == pytest.approx(np.var(vals),
                                                     rel=1e-8)
        assert out.columns["mv"][i] == pytest.approx(np.mean(vals),
                                                     rel=1e-9)


def test_planner_udaf_compile_knob_forces_generic(_fresh_verdicts,
                                                  monkeypatch):
    """ARROYO_UDAF_COMPILE=off pins the pre-PR buffered plan shape (the
    A/B axis) — and the generic path still computes the same numbers."""
    from arroyo_tpu.graph.logical import OpKind

    monkeypatch.setenv("ARROYO_UDAF_COMPILE", "off")
    p = SchemaProvider()
    p.register_udaf("my_var", np.var)
    events_table(p)
    sql = ("CREATE TABLE out WITH (connector='memory', name='results');"
           "INSERT INTO out SELECT k, my_var(v) as vv FROM events "
           "GROUP BY k, tumble(interval '1 second')")
    prog = plan_sql(sql, p)
    kinds = [prog.node(op).operator.kind for op in prog.graph.nodes]
    assert OpKind.WINDOW in kinds
    out = run_sql(sql, p)
    src = events_table(SchemaProvider()).get("events").config["batches"][0]
    groups = {}
    for t, k, v in zip(src.timestamp.tolist(), src.columns["k"].tolist(),
                       src.columns["v"].tolist()):
        groups.setdefault((k, (t // SEC + 1) * SEC), []).append(v)
    for i in range(len(out)):
        key = (int(out.columns["k"][i]), int(out.columns["window_end"][i]))
        assert out.columns["vv"][i] == pytest.approx(
            np.var(np.asarray(groups[key])), rel=1e-8)
