"""SQL UDF / UDAF registration and execution (the reference's Rust-UDF
registration arroyo-sql/src/lib.rs:196-290 + worker execution
operators/mod.rs:347-494), including BASELINE.md config #5: session-window
aggregation with a UDAF over a Kafka source with checkpoint/restore."""

import asyncio
import json

import numpy as np
import pytest

from arroyo_tpu import Batch
from arroyo_tpu.connectors.kafka import InMemoryKafkaBroker
from arroyo_tpu.connectors.memory import clear_sink, sink_output
from arroyo_tpu.engine.engine import Engine, LocalRunner
from arroyo_tpu.sql import (
    SchemaProvider,
    SqlPlanError,
    plan_sql,
    unregister_udfs,
)
from arroyo_tpu.types import StopMode

SEC = 1_000_000


@pytest.fixture(autouse=True)
def _clean_udfs():
    yield
    unregister_udfs()


def run_sql(sql, provider=None):
    clear_sink("results")
    prog = plan_sql(sql, provider)
    LocalRunner(prog).run()
    outs = sink_output("results")
    return Batch.concat(outs) if outs else None


def events_table(p, n=200):
    rng = np.random.default_rng(3)
    ts = np.sort(rng.integers(0, 3 * SEC, n)).astype(np.int64)
    p.add_memory_table("events", {"k": "i", "v": "f", "name": "s"}, [
        Batch(ts, {"k": rng.integers(0, 4, n).astype(np.int64),
                   "v": rng.random(n).astype(np.float64) * 100,
                   "name": np.array([f"u{i % 3}" for i in range(n)],
                                    dtype=object)})])
    return p


def test_scalar_udf_in_projection():
    p = SchemaProvider()
    p.register_udf("add_tax", lambda v: v * 1.2)
    p.register_udf("shout", lambda s: np.array(
        [x.upper() + "!" if x is not None else None for x in s],
        dtype=object))
    events_table(p)
    out = run_sql("SELECT add_tax(v) as taxed, shout(name) as n2, v "
                  "FROM events WHERE add_tax(v) > 60", p)
    assert out is not None and len(out) > 0
    np.testing.assert_allclose(out.columns["taxed"],
                               np.asarray(out.columns["v"]) * 1.2,
                               rtol=1e-6)
    assert np.all(out.columns["taxed"] > 60)
    assert set(np.unique(list(out.columns["n2"]))) <= {"U0!", "U1!", "U2!"}


def test_udaf_tumbling_window_matches_numpy():
    p = SchemaProvider()
    p.register_udaf("median", np.median)
    p.register_udaf("p90", lambda v: float(np.percentile(v, 90)))
    events_table(p)
    out = run_sql(
        "SELECT k, median(v) as med, p90(v) as p90v, count(*) as cnt "
        "FROM events GROUP BY k, tumble(interval '1 second')", p)
    assert out is not None
    # oracle: recompute per (key, window) from the source batch
    src = events_table(SchemaProvider()).get("events").config["batches"][0]
    groups = {}
    for t, k, v in zip(src.timestamp.tolist(), src.columns["k"].tolist(),
                       src.columns["v"].tolist()):
        groups.setdefault((k, (t // SEC + 1) * SEC), []).append(v)
    for i in range(len(out)):
        key = (int(out.columns["k"][i]), int(out.columns["window_end"][i]))
        vals = np.asarray(groups[key])
        assert out.columns["cnt"][i] == len(vals)
        assert out.columns["med"][i] == pytest.approx(np.median(vals))
        assert out.columns["p90v"][i] == pytest.approx(
            np.percentile(vals, 90))


def test_udaf_without_window_rejected():
    p = SchemaProvider()
    p.register_udaf("median", np.median)
    events_table(p)
    with pytest.raises(SqlPlanError, match="requires a window"):
        plan_sql("CREATE TABLE out WITH (connector='memory', "
                 "name='results'); INSERT INTO out "
                 "SELECT k, median(v) FROM events GROUP BY k", p)


def test_udf_cannot_shadow_builtin():
    p = SchemaProvider()
    with pytest.raises(ValueError, match="shadow"):
        p.register_udf("upper", lambda s: s)
    with pytest.raises(ValueError, match="shadow"):
        p.register_udaf("sum", np.sum)


def test_baseline5_session_udaf_kafka_checkpoint(tmp_path):
    """BASELINE.md config #5: session-window aggregation with a UDAF over
    a Kafka source, with a checkpoint + restore in the middle of an OPEN
    session — the buffered session state must survive the restore and the
    session must close with every value from both runs."""
    InMemoryKafkaBroker.reset("u5")
    broker = InMemoryKafkaBroker.get("u5")
    broker.create_topic("sess", partitions=1)

    # run-1 events: key 1 session [0.0s, 1.0s], key 2 single at 0.2s
    run1 = [(1, 10.0, 0), (1, 30.0, 500_000), (2, 5.0, 200_000),
            (1, 20.0, 1_000_000)]
    for k, v, ts in run1:
        broker.produce("sess", json.dumps(
            {"k": k, "v": v, "ts": ts * 1000}).encode(), partition=0)

    p = SchemaProvider()
    p.register_udaf("median", np.median)
    sql = """
    CREATE TABLE ev (
      k BIGINT, v DOUBLE, ts BIGINT,
      event_time TIMESTAMP GENERATED ALWAYS AS
        (CAST(from_unixtime(ts) as TIMESTAMP))
    ) WITH (
      connector = 'kafka', bootstrap_servers = 'memory://u5',
      topic = 'sess', type = 'source', format = 'json',
      event_time_field = 'event_time', batch_size = '2'
    );
    CREATE TABLE out WITH (connector = 'memory', name = 'results');
    INSERT INTO out
    SELECT k, median(v) as med, count(*) as cnt,
           session(INTERVAL '1' SECOND) as window
    FROM ev GROUP BY 1, 4
    """
    url = f"file://{tmp_path}/ckpt"
    clear_sink("results")

    async def run_phase(restore, epoch, settle_secs):
        prog = plan_sql(sql, p)
        eng = Engine.for_local(prog, "udaf-job", checkpoint_url=url,
                               restore_epoch=restore)
        running = eng.start()
        await asyncio.sleep(settle_secs)  # let the source drain the topic
        await running.checkpoint(epoch)
        assert await running.wait_for_checkpoint(epoch)
        await running.stop(StopMode.IMMEDIATE)
        try:
            await running.join()
        except RuntimeError:
            pass

    # phase 1: consume the run-1 events, checkpoint with the session OPEN
    # (max run-1 event time is 1.0s and lateness is 1s, so the watermark
    # cannot reach any session end — nothing may fire before the restore)
    asyncio.run(run_phase(None, 1, 0.6))
    assert not sink_output("results"), "session fired before its gap closed"

    # run-2 events: key 1's session EXTENDS at 1.4s (gap 1s from 1.0s),
    # then a far event advances the watermark past the session end
    run2 = [(1, 40.0, 1_400_000), (1, 99.0, 10_000_000)]
    for k, v, ts in run2:
        broker.produce("sess", json.dumps(
            {"k": k, "v": v, "ts": ts * 1000}).encode(), partition=0)

    asyncio.run(run_phase(1, 2, 0.8))
    out = Batch.concat(sink_output("results"))
    rows = {}
    for i in range(len(out)):
        rows[(int(out.columns["k"][i]),
              int(out.columns["window_start"][i]))] = (
            int(out.columns["cnt"][i]), float(out.columns["med"][i]))
    # key 1 session [0, 2.4s): all four values, including the three
    # buffered BEFORE the checkpoint -> median(10, 20, 30, 40) = 25
    assert rows[(1, 0)] == (4, 25.0)
    # key 2 session [0.2s, 1.2s)
    assert rows[(2, 200_000)] == (1, 5.0)


def test_udaf_distinct_and_arity_rejected():
    p = SchemaProvider()
    p.register_udaf("median", np.median)
    events_table(p)
    base = ("CREATE TABLE out WITH (connector='memory', name='results');"
            "INSERT INTO out ")
    with pytest.raises(SqlPlanError, match="DISTINCT"):
        plan_sql(base + "SELECT k, median(DISTINCT v) FROM events "
                 "GROUP BY k, tumble(interval '1 second')", p)
    with pytest.raises(SqlPlanError, match="exactly one column"):
        plan_sql(base + "SELECT k, median(v, k) FROM events "
                 "GROUP BY k, tumble(interval '1 second')", p)
