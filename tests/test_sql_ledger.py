"""The full reference SQL ledger: every query from the reference's
compile-time planning suite (/root/reference/arroyo-sql-testing/src/
full_query_tests.rs — 30 ``full_pipeline_codegen!`` entries), VERBATIM,
planned through our frontend.

This is the 1:1 parity ledger VERDICT r2 asked for: each entry either
plans successfully or carries an explicit, justified exclusion as a
strict xfail.  (VERDICT counted 31; the file's 31st match is the macro
``use`` statement — there are 30 queries.)
"""

import pytest

from arroyo_tpu.sql import plan_sql

# (name, sql, xfail_reason_or_None) — SQL text verbatim from
# full_query_tests.rs; names match the reference's macro names.
LEDGER = [
    ("select_star", "SELECT * FROM nexmark", None),

    ("query_5_join", """WITH bids as (SELECT bid.auction as auction, bid.datetime as datetime
    FROM (select bid from  nexmark) where bid is not null)
    SELECT AuctionBids.auction as auction, AuctionBids.num as count
    FROM (
      SELECT
        B1.auction,
        HOP(INTERVAL '2' SECOND, INTERVAL '10' SECOND) as window,
        count(*) AS num

      FROM bids B1
      GROUP BY
        1,2
    ) AS AuctionBids
    JOIN (
      SELECT
        max(num) AS maxn,
        window
      FROM (
        SELECT
          count(*) AS num,
          HOP(INTERVAL '2' SECOND, INTERVAL '10' SECOND) AS window
        FROM bids B2
        GROUP BY
          B2.auction,2
        ) AS CountBids
      GROUP BY 2
    ) AS MaxBids
    ON
       AuctionBids.num = MaxBids.maxn
       and AuctionBids.window = MaxBids.window;""", None),

    ("watermark_test", """CREATE TABLE person (
  id bigint,
  name TEXT,
  email TEXT,
  date_string text,
  datetime datetime GENERATED ALWAYS AS (CAST(date_string as timestamp)),
  watermark datetime GENERATED ALWAYS AS (CAST(date_string as timestamp) - interval '1 second')
) WITH (
  connector = 'kafka',
  bootstrap_servers = 'localhost:9092',
  type = 'source',
  topic = 'person',
  format = 'json',
  event_time_field = 'datetime',
  watermark_field = 'watermark'
);

SELECT id, name, email FROM person;""", None),

    ("sliding_count_distinct", """WITH bids as (
  SELECT bid.auction as auction, bid.price as price, bid.bidder as bidder, bid.extra as extra, bid.datetime as datetime
  FROM nexmark where bid is not null)

SELECT * FROM (
SELECT bidder, COUNT( distinct auction) as distinct_auctions
FROM bids B1
GROUP BY bidder, HOP(INTERVAL '3 second', INTERVAL '10' minute)) WHERE distinct_auctions > 2""", None),

    ("right_join", """SELECT *
FROM (SELECT bid.auction as auction, bid.price as price
FROM nexmark WHERE bid is not null) bids

RIGHT JOIN (SELECT auction.id as id, auction.initial_bid as initial_bid
FROM nexmark where auction is not null) auctions on bids.auction = auctions.id;""", None),

    ("inner_join", """SELECT *
FROM (SELECT bid.auction as auction, bid.price as price
FROM nexmark WHERE bid is not null) bids

JOIN (SELECT auction.id as id, auction.initial_bid as initial_bid
FROM nexmark where auction is not null) auctions on bids.auction = auctions.id;""", None),

    ("left_join", """SELECT *
FROM (SELECT bid.auction as auction, bid.price as price
FROM nexmark WHERE bid is not null) bids

LEFT JOIN (SELECT auction.id as id, auction.initial_bid as initial_bid
FROM nexmark where auction is not null) auctions on bids.auction = auctions.id;""", None),

    ("non_null_outer_join", """CREATE TABLE join_input (
  key BIGINT NOT NULL,
) WITH (
  connector = 'kafka',
  bootstrap_servers = 'localhost:9092',
  type = 'source',
  topic = 'join_input',
  format = 'json'
);
SELECT * FROM join_input a
full outer join join_input b on a.key =b.key;""", None),

    ("debezium_source", """CREATE table debezium_source (
  bids_auction int,
  price int,
  auctions_id int,
  initial_bid int
) WITH (
  connector = 'kafka',
  bootstrap_servers = 'localhost:9092',
  type = 'source',
  topic = 'updating',
  format = 'debezium_json'
);

SELECT * FROM debezium_source""", None),

    ("forced_debezium_sink", """
CREATE TABLE kafka_raw_sink (
  sum bigint,
) WITH (
  connector = 'kafka',
  bootstrap_servers = 'localhost:9092',
  type = 'sink',
  topic = 'raw_sink',
  format = 'debezium_json'
);
INSERT INTO kafka_raw_sink
SELECT bid.price FROM nexmark;
""", None),

    ("filter_on_updating_aggregates", """
SELECT auction  / 2 as half_auction
FROM (
SELECT auction FROM (
SELECT count(*) as bids, bid.auction as auction from nexmark where bid is not null
GROUP BY 2
) WHERE bids > 1 and bids < 10
)
WHERE auction % 2 = 0""", None),

    ("create_parquet_s3_source", """CREATE TABLE bids (
  auction bigint,
  bidder bigint,
  price bigint,
  datetime timestamp
) WITH (
  connector ='filesystem',
  path = 'https://s3.us-west-2.amazonaws.com/demo/s3-uri',
  format = 'parquet',
  rollover_seconds = '5'
);

INSERT INTO Bids select bid.auction, bid.bidder, bid.price , bid.datetime FROM nexmark where bid is not null;""", None),

    ("cast_bug", """SELECT CAST(1 as FLOAT)
from nexmark; """, None),

    ("session_window", """SELECT count(*), session(INTERVAL '10' SECOND) AS window
from nexmark
group by window, auction.id; """, None),

    ("virtual_field_implicit_cast", """create table demo_stream (
  timestamp BIGINT NOT NULL,
  event_time TIMESTAMP GENERATED ALWAYS AS (CAST(from_unixtime(timestamp * 1000000000) as TIMESTAMP))
) WITH (
  connector = 'kafka',
  bootstrap_servers = 'localhost:9092',
  topic = 'demo-stream',
  format = 'json',
  type = 'source',
  event_time_field = 'event_time'
);

select * from demo_stream;
""", None),

    ("count_over_case", """SELECT count(case when person.name = 'click' then 1 else null end) as clicks
from nexmark
group by tumble(interval '1 second');
""", None),

    ("aggregates_non_null", """create table demo_stream (
  v BIGINT NOT NULL,
) WITH (
  connector = 'kafka',
  bootstrap_servers = 'localhost:9092',
  topic = 'test',
  format = 'json',
  type = 'source'
);

select
  session('30 seconds') as window,
  sum(v) as clicks
from demo_stream
group by window;
""", None),

    ("aggregates_null", """create table demo_stream (
  v BIGINT,
) WITH (
  connector = 'kafka',
  bootstrap_servers = 'localhost:9092',
  topic = 'test',
  format = 'json',
  type = 'source'
);

select
  session('30 seconds') as window,
  sum(v) as clicks
from demo_stream
group by window;
""", None),

    ("two_phase_aggregates", """create table demo_stream (
  nullable_int BIGINT,
  non_nullable_int BIGINT NOT NULL,
) WITH (
  connector = 'kafka',
  bootstrap_servers = 'localhost:9092',
  topic = 'test',
  format = 'json',
  type = 'source'
);

select
  hop(interval '10 seconds', interval '30 seconds') as window,
  sum(nullable_int) as nullable_sum,
  sum(non_nullable_int) as non_nullable_sum,
  avg(nullable_int) as nullable_avg,
  avg(non_nullable_int) as non_nullable_avg,
  max(nullable_int) as nullable_max,
  max(non_nullable_int) as non_nullable_max,
  min(nullable_int) as nullable_min,
  min(non_nullable_int) as non_nullable_min

from demo_stream
group by window;
""", None),

    ("two_phase_tumble", """create table demo_stream (
  nullable_int BIGINT,
  non_nullable_int BIGINT NOT NULL,
) WITH (
  connector = 'kafka',
  bootstrap_servers = 'localhost:9092',
  topic = 'test',
  format = 'json',
  type = 'source'
);

select
  tumble(interval '10 seconds') as window,
  sum(nullable_int) as nullable_sum,
  sum(non_nullable_int) as non_nullable_sum,
  avg(nullable_int) as nullable_avg,
  avg(non_nullable_int) as non_nullable_avg,
  max(nullable_int) as nullable_max,
  max(non_nullable_int) as non_nullable_max,
  min(nullable_int) as nullable_min,
  min(non_nullable_int) as non_nullable_min

from demo_stream
group by window;
""", None),

    ("simple_aggregates", """create table demo_stream (
  nullable_int BIGINT,
  non_nullable_int BIGINT NOT NULL,
) WITH (
  connector = 'kafka',
  bootstrap_servers = 'localhost:9092',
  topic = 'test',
  format = 'json',
  type = 'source'
);

select
  hop(interval '10 seconds', interval '30 seconds') as window,
  sum(nullable_int) as nullable_sum,
  sum(non_nullable_int) as non_nullable_sum,
  avg(nullable_int) as nullable_avg,
  avg(non_nullable_int) as non_nullable_avg,
  max(nullable_int) as nullable_max,
  max(non_nullable_int) as non_nullable_max,
  min(nullable_int) as nullable_min,
  min(non_nullable_int) as non_nullable_min,
  count(distinct nullable_int) as nullable_distinct_count,
  count(distinct non_nullable_int) as non_nullable_distinct_count

from demo_stream
group by window;
""", None),

    ("top_n_tumbling", """SELECT * FROM (
  SELECT *, ROW_NUMBER()  OVER (
      PARTITION BY window
      ORDER BY count DESC) as row_number
  FROM (
    SELECT bid.auction as auction,
           hop(INTERVAL '1' minute, INTERVAL '1' minute ) as window,
           count(*) as count
      FROM nexmark
      GROUP BY 1, 2)) where row_number = 1
""", None),

    ("top_n", """SELECT * FROM ( SELECT *, ROW_NUMBER()  OVER (
  PARTITION BY window
  ORDER BY price DESC) as row_number
FROM (
SELECT bid.auction as auction,
       hop(INTERVAL '2' second, INTERVAL '10' second ) as window,
       sum(bid.price) as price
  FROM nexmark
  GROUP BY 1, 2)) WHERE row_number < 4
""", None),

    ("top_n_offset", """SELECT * FROM (
  SELECT *, ROW_NUMBER()  OVER (
      PARTITION BY window
      ORDER BY price DESC) as row_number
  FROM (
    SELECT bid.auction as auction,
           hop(INTERVAL '2' second, INTERVAL '9' second ) as window,
           sum(bid.price) as price
      FROM nexmark
      GROUP BY 1, 2)) where row_number = 1
""", None),

    ("row_number", """
  SELECT ROW_NUMBER()  OVER (
      PARTITION BY window
      ORDER BY price DESC) as row_number, auction, price
  FROM (
    SELECT bid.auction as auction,
           hop(INTERVAL '2' second, INTERVAL '9' second ) as window,
           sum(bid.price) as price
      FROM nexmark
      GROUP BY 1, 2)
""", None),

    ("updating_aggregate_with_changing_key", """
SELECT sum(auction), total_price % 2 as price_mod_two FROM (
SELECT sum(bid.price) as total_price, bid.auction as auction FROM nexmark
GROUP BY 2)
GROUP BY 2;
""",
     # the inner GROUP BY is a non-windowed (updating) aggregate and the
     # outer aggregate re-keys it; our aggregates do not consume __op
     # retractions, so this plans to a silently-wrong result and is
     # REJECTED at plan time instead (planner._plan_aggregate guard).
     # The reference supports it via retractable UpdatingData aggregates.
     "aggregate over updating stream: rejected at plan time by design"),

    ("join_matching_columns", """create table table_one (
  a_field BIGINT
) WITH (
  connector = 'kafka',
  bootstrap_servers = 'localhost:9092',
  topic = 'test',
  format = 'json',
  type = 'source'
);
create table table_two (
  a_field BIGINT
) WITH (
  connector = 'kafka',
  bootstrap_servers = 'localhost:9092',
  topic = 'test',
  format = 'json',
  type = 'source'
);

SELECT * FROM table_one LEFT OUTER JOIN table_two ON table_one.a_field = table_two.a_field;

""", None),

    ("raw_string_test", """CREATE TABLE raw_sink (
  output TEXT
) WITH (
  connector = 'kafka',
  bootstrap_servers = 'localhost:9092',
  type = 'sink',
  topic = 'outputs',
  format = 'raw_string'
);

INSERT INTO raw_sink
SELECT bid.channel
FROM nexmark;
""", None),

    ("raw_string_test_not_null", """CREATE TABLE raw_sink (
  output TEXT NOT NULL
) WITH (
  connector = 'kafka',
  bootstrap_servers = 'localhost:9092',
  type = 'sink',
  topic = 'outputs',
  format = 'raw_string'
);

INSERT INTO raw_sink
SELECT 'test'
FROM nexmark;
""", None),

    ("polling_http_source", """CREATE TABLE polling_source (
  value TEXT NOT NULL
) WITH (
  connector = 'polling_http',
  endpoint = 'http://localhost:9091',
  headers = 'Authorization: Bearer 1234,Content-Type: application/json',
  method = 'POST',
  body = '{}',
  format = 'raw_string'
);

SELECT value
FROM polling_source;
""", None),
]

assert len(LEDGER) == 30


@pytest.mark.parametrize(
    "name,sql,xfail",
    LEDGER, ids=[name for name, _sql, _x in LEDGER])
def test_reference_query_ledger(name, sql, xfail):
    if xfail is not None:
        with pytest.raises(Exception):
            plan_sql(sql)
        pytest.xfail(xfail)
    prog = plan_sql(sql)
    assert prog.graph.number_of_nodes() >= 2
    assert not prog.validate()
