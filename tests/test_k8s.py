"""KubernetesScheduler against a fake API (the reference tests its
ReplicaSet construction the same way, kubernetes.rs:245-343)."""

import asyncio

import pytest

from arroyo_tpu.controller.scheduler import (
    InProcessScheduler,
    KubernetesScheduler,
    ProcessScheduler,
    scheduler_from_env,
)


class FakeK8sApi:
    def __init__(self):
        self.created = []
        self.deleted = []
        self.pods = []

    def create_replicaset(self, manifest):
        self.created.append(manifest)
        return manifest

    def delete_replicasets(self, namespace, label_selector):
        self.deleted.append((namespace, label_selector))
        return {}

    def list_pods(self, namespace, label_selector):
        return {"items": self.pods}


def test_replicaset_manifest_shape(monkeypatch):
    monkeypatch.setenv("K8S_NAMESPACE", "streaming")
    monkeypatch.setenv("K8S_WORKER_IMAGE", "registry/worker:v2")
    monkeypatch.setenv("K8S_WORKER_LABELS", '{"team": "data"}')
    api = FakeK8sApi()
    s = KubernetesScheduler(client=api)
    asyncio.run(s.start_workers("job_ab", "http://ctl:9190", 3, 4))

    assert len(api.created) == 1
    rs = api.created[0]
    assert rs["kind"] == "ReplicaSet"
    assert rs["metadata"]["namespace"] == "streaming"
    assert rs["metadata"]["labels"]["job_id"] == "job_ab"
    assert rs["metadata"]["labels"]["team"] == "data"
    assert "_" not in rs["metadata"]["name"]  # k8s name rules
    assert rs["spec"]["replicas"] == 3
    assert rs["spec"]["selector"]["matchLabels"]["job_id"] == "job_ab"
    c = rs["spec"]["template"]["spec"]["containers"][0]
    assert c["image"] == "registry/worker:v2"
    env = {e["name"]: e["value"] for e in c["env"]}
    assert env["JOB_ID"] == "job_ab"
    assert env["CONTROLLER_ADDR"] == "http://ctl:9190"
    assert env["TASK_SLOTS"] == "4"


def test_tpu_pool_slots_map_to_chips(monkeypatch):
    """TPU node pools: slots = chips; the pod requests google.com/tpu so
    GKE places one worker per TPU host, and the worker's mesh path shards
    state over its chips (SURVEY #34: 'slots = chips')."""
    monkeypatch.setenv("K8S_WORKER_TPU_CHIPS", "8")
    api = FakeK8sApi()
    s = KubernetesScheduler(client=api)
    assert s.slots_per_pod == 8
    asyncio.run(s.start_workers("j1", "http://ctl:9190", 2, 8))
    c = api.created[0]["spec"]["template"]["spec"]["containers"][0]
    assert c["resources"]["limits"]["google.com/tpu"] == "8"
    env = {e["name"]: e["value"] for e in c["env"]}
    assert env["ARROYO_MESH"] == "auto"


def test_stop_and_list_workers(monkeypatch):
    api = FakeK8sApi()
    s = KubernetesScheduler(client=api)
    asyncio.run(s.start_workers("j2", "http://ctl:9190", 2, 4))
    api.pods = [
        {"metadata": {"name": "w-1"}, "status": {"phase": "Running"}},
        {"metadata": {"name": "w-2"}, "status": {"phase": "Pending"}},
        {"metadata": {"name": "w-3"}, "status": {"phase": "Failed"}},
    ]
    assert s.workers_for_job("j2") == ["w-1", "w-2"]
    asyncio.run(s.stop_workers("j2"))
    ns, sel = api.deleted[0]
    assert ns == "default" and "job_id=j2" in sel


def test_scheduler_from_env(monkeypatch):
    monkeypatch.setenv("SCHEDULER", "k8s")
    assert isinstance(scheduler_from_env(), KubernetesScheduler)
    monkeypatch.setenv("SCHEDULER", "embedded")
    assert isinstance(scheduler_from_env(), InProcessScheduler)
    monkeypatch.delenv("SCHEDULER")
    assert isinstance(scheduler_from_env(), ProcessScheduler)


def test_out_of_cluster_fails_loudly(monkeypatch):
    monkeypatch.delenv("KUBERNETES_SERVICE_HOST", raising=False)
    s = KubernetesScheduler()  # no client injected
    with pytest.raises(RuntimeError, match="Kubernetes"):
        asyncio.run(s.start_workers("j3", "http://ctl:9190", 1, 1))


# ---------------------------------------------------------------------------
# nomad
# ---------------------------------------------------------------------------


class FakeNomadApi:
    def __init__(self):
        self.submitted = []
        self.deleted = []

    def submit_job(self, job):
        self.submitted.append(job)
        return {"EvalID": "e1"}

    def list_jobs(self, prefix):
        out = []
        for j in self.submitted:
            job = j["Job"]
            if job["ID"].startswith(prefix):
                status = ("dead" if job["ID"] in self.deleted else "running")
                out.append({"ID": job["ID"], "Name": job["Name"],
                            "Status": status, "Meta": job["Meta"]})
        return out

    def delete_job(self, name):
        self.deleted.append(name)
        return {}


def test_nomad_job_shape_and_lifecycle():
    from arroyo_tpu.controller.scheduler import NomadScheduler

    api = FakeNomadApi()
    s = NomadScheduler(client=api)
    asyncio.run(s.start_workers("job_x", "http://ctl:9190", 2, 5))

    assert len(api.submitted) == 2
    job = api.submitted[0]["Job"]
    assert job["Type"] == "batch"
    # controller owns failures: nomad must not restart/reschedule — and
    # these policies live on the TaskGroup in the JSON API
    group = job["TaskGroups"][0]
    assert group["RestartPolicy"] == {"Attempts": 0, "Mode": "fail"}
    assert group["ReschedulePolicy"] == {"Attempts": 0, "Unlimited": False}
    task = group["Tasks"][0]
    assert task["Env"]["TASK_SLOTS"] == "5"
    assert task["Env"]["JOB_ID"] == "job_x"
    assert task["Env"]["CONTROLLER_ADDR"] == "http://ctl:9190"
    assert task["Resources"]["CPU"] == 3400 * 5

    workers = s.workers_for_job("job_x")
    assert len(workers) == 2
    assert all(w.isdigit() for w in workers)

    asyncio.run(s.stop_workers("job_x"))
    assert len(api.deleted) == 2
    assert s.workers_for_job("job_x") == []  # dead jobs are filtered


def test_nomad_restart_scopes_to_latest_run():
    """workers_for_job only sees the current run's jobs, so a stale
    still-terminating worker from the previous run is not double-counted
    (nomad.rs:68-72 prefixes by run_id)."""
    from arroyo_tpu.controller.scheduler import NomadScheduler

    api = FakeNomadApi()
    s = NomadScheduler(client=api)
    asyncio.run(s.start_workers("job_y", "http://ctl:9190", 1, 2))
    first = s.workers_for_job("job_y")
    # restart: run_id increments; old run's job still listed as running
    asyncio.run(s.start_workers("job_y", "http://ctl:9190", 1, 2))
    second = s.workers_for_job("job_y")
    assert len(second) == 1
    assert first != second


def test_scheduler_from_env_nomad(monkeypatch):
    monkeypatch.setenv("SCHEDULER", "nomad")
    from arroyo_tpu.controller.scheduler import NomadScheduler

    assert isinstance(scheduler_from_env(), NomadScheduler)
