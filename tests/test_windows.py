"""Window operator correctness vs numpy oracles: tumbling/sliding bin
aggregation (the reference's aggregating_window semantics), generic windows,
sessions (merge/extend, windows.rs:430-636 test analog), TopN, and joins."""

import asyncio

import numpy as np
import pytest

from arroyo_tpu import AggKind, AggSpec, Batch, Program, SessionWindow, \
    SlidingWindow, Stream, TumblingWindow
from arroyo_tpu.connectors.memory import clear_sink, sink_output
from arroyo_tpu.engine.engine import LocalRunner

MS = 1_000  # micros
SEC = 1_000_000


def make_events(rng, n=5000, n_keys=20, t0=0, span=10 * SEC):
    ts = np.sort(rng.integers(t0, t0 + span, n)).astype(np.int64)
    keys = rng.integers(0, n_keys, n).astype(np.int64)
    vals = rng.integers(1, 100, n).astype(np.int64)
    return Batch(ts, {"k": keys, "v": vals})


def run_pipeline(batches, build, sink="out"):
    clear_sink(sink)
    prog = build(Stream.source("memory", {"batches": batches})
                 .watermark(max_lateness_micros=0))
    LocalRunner(prog).run()
    outs = sink_output(sink)
    return Batch.concat(outs) if outs else None


def oracle_windows(ts, keys, vals, width, slide):
    """Expected (key, window_end) -> (count, sum, min, max)."""
    out = {}
    for t, k, v in zip(ts.tolist(), keys.tolist(), vals.tolist()):
        first_end = (t // slide + 1) * slide
        e = first_end
        while e - width <= t < e:
            c, s, mn, mx = out.get((k, e), (0, 0, 1 << 60, -(1 << 60)))
            out[(k, e)] = (c + 1, s + v, min(mn, v), max(mx, v))
            e += slide
    return out


@pytest.mark.parametrize("width,slide", [(SEC, SEC), (2 * SEC, SEC),
                                         (SEC, 250 * MS)])
def test_bin_agg_matches_oracle(rng, width, slide):
    ev = make_events(rng)
    aggs = [AggSpec(AggKind.COUNT, None, "cnt"),
            AggSpec(AggKind.SUM, "v", "total"),
            AggSpec(AggKind.MIN, "v", "lo"),
            AggSpec(AggKind.MAX, "v", "hi")]
    out = run_pipeline(
        [ev],
        lambda s: s.key_by("k").sliding_aggregate(width, slide, aggs)
        .sink("memory", {"name": "out"}),
    )
    assert out is not None
    expected = oracle_windows(ev.timestamp, ev.columns["k"], ev.columns["v"],
                              width, slide)
    got = {}
    for i in range(len(out)):
        key = (int(out.columns["k"][i]), int(out.columns["window_end"][i]))
        got[key] = (int(out.columns["cnt"][i]), int(out.columns["total"][i]),
                    int(out.columns["lo"][i]), int(out.columns["hi"][i]))
    assert got == expected


def test_tumbling_agg_multiple_batches(rng):
    evs = [make_events(rng, n=1000, t0=i * SEC, span=SEC) for i in range(5)]
    aggs = [AggSpec(AggKind.COUNT, None, "cnt")]
    out = run_pipeline(
        evs,
        lambda s: s.key_by("k").tumbling_aggregate(SEC, aggs)
        .sink("memory", {"name": "out"}),
    )
    total = int(out.columns["cnt"].sum())
    assert total == 5000  # every event in exactly one tumbling window


def test_generic_window_aggregate(rng):
    ev = make_events(rng, n=2000, span=4 * SEC)
    aggs = [AggSpec(AggKind.COUNT, None, "cnt"),
            AggSpec(AggKind.AVG, "v", "avg_v")]
    out = run_pipeline(
        [ev],
        lambda s: s.key_by("k").window(TumblingWindow(SEC), aggs)
        .sink("memory", {"name": "out"}),
    )
    assert int(out.columns["cnt"].sum()) == 2000
    # avg within plausible range
    assert np.all(out.columns["avg_v"] >= 1) and np.all(out.columns["avg_v"] < 100)
    # key column values preserved
    assert "k" in out.columns


def test_generic_window_flatten(rng):
    ev = make_events(rng, n=500, span=2 * SEC)
    out = run_pipeline(
        [ev],
        lambda s: s.key_by("k").window(TumblingWindow(SEC), flatten=True)
        .sink("memory", {"name": "out"}),
    )
    assert len(out) == 500
    assert "window_end" in out.columns


def test_session_windows_merge():
    # key 1: events at 0, 1s, 2s with 1.5s gap -> one session [0, 2s+gap)
    # key 2: events at 0 and 5s -> two sessions
    gap = 1500 * MS
    ts = np.array([0, 1 * SEC, 2 * SEC, 0, 5 * SEC], dtype=np.int64)
    keys = np.array([1, 1, 1, 2, 2], dtype=np.int64)
    vals = np.ones(5, dtype=np.int64)
    ev = Batch(ts, {"k": keys, "v": vals})
    aggs = [AggSpec(AggKind.COUNT, None, "cnt")]
    out = run_pipeline(
        [ev],
        lambda s: s.key_by("k").window(SessionWindow(gap), aggs)
        .sink("memory", {"name": "out"}),
    )
    rows = sorted(
        (int(out.columns["k"][i]), int(out.columns["cnt"][i]),
         int(out.columns["window_start"][i]))
        for i in range(len(out)))
    assert rows == [(1, 3, 0), (2, 1, 0), (2, 1, 5 * SEC)]


def test_session_windows_max_size_clamp_splits():
    """Events chaining past the MAX_SESSION_SIZE clamp must START a new
    session (reference windows.rs clamp), not be swallowed by the
    vectorized interval merge (r4 review finding: the clamped union
    would silently drop the tail events)."""
    from arroyo_tpu.engine.operators_window import MAX_SESSION_SIZE_MICROS

    gap = 10 * SEC
    MAX = MAX_SESSION_SIZE_MICROS
    # batch 1: a 9s-spaced chain to MAX-5s — the per-event path (span_ok
    # routes there) clamps the merged session to [0, MAX).  batch 2:
    # events at MAX-1 (inside the clamped session) and MAX+2 — the
    # interval merge would clamp-truncate past MAX+2, so it must fall
    # back and split: MAX-1 joins session 1, MAX+2 opens session 2.
    ts1 = np.arange(0, MAX - 5 * SEC + 1, 9 * SEC, dtype=np.int64)
    ts2 = np.array([MAX - 1, MAX + 2], dtype=np.int64)
    aggs = [AggSpec(AggKind.COUNT, None, "cnt")]
    out = run_pipeline(
        [Batch(ts1, {"k": np.full(len(ts1), 7, np.int64),
                     "v": np.ones(len(ts1), np.int64)}),
         Batch(ts2, {"k": np.full(2, 7, np.int64),
                     "v": np.ones(2, np.int64)})],
        lambda s: s.key_by("k").window(SessionWindow(gap), aggs)
        .sink("memory", {"name": "out"}),
    )
    rows = sorted((int(out.columns["window_start"][i]),
                   int(out.columns["cnt"][i]))
                  for i in range(len(out)))
    assert rows == [(0, len(ts1) + 1), (MAX + 2, 1)], rows


def test_tumbling_top_n(rng):
    ev = make_events(rng, n=3000, n_keys=50, span=3 * SEC)
    out = run_pipeline(
        [ev],
        lambda s: s.key_by("k")
        .tumbling_aggregate(SEC, [AggSpec(AggKind.COUNT, None, "cnt")])
        .tumbling_top_n(SEC, 5, "cnt")
        .sink("memory", {"name": "out"}),
    )
    # at most 5 rows per window
    from collections import Counter

    per_window = Counter(out.columns["window_end"].tolist())
    assert all(v <= 5 for v in per_window.values())
    assert len(out) > 0


def test_window_join():
    # left: persons, right: auctions keyed by person/seller id
    t = lambda s: s * SEC
    lts = np.array([t(0.1), t(0.2), t(1.2)], dtype=np.int64)
    l = Batch(lts, {"pid": np.array([1, 2, 3], dtype=np.int64),
                    "name": np.array(["a", "b", "c"], dtype=object)})
    rts = np.array([t(0.3), t(0.4), t(0.5), t(1.5)], dtype=np.int64)
    r = Batch(rts, {"pid": np.array([1, 1, 9, 3], dtype=np.int64),
                    "auction": np.array([10, 11, 12, 13], dtype=np.int64)})

    clear_sink("out")
    from arroyo_tpu.graph.logical import TumblingWindow

    left = (Stream.source("memory", {"batches": [l]})
            .watermark(max_lateness_micros=0).key_by("pid"))
    right = (Stream.source("memory", {"batches": [r]},
                           program=left.program)
             .watermark(max_lateness_micros=0).key_by("pid"))
    prog = (left.window_join(right, TumblingWindow(SEC))
            .sink("memory", {"name": "out"}))
    LocalRunner(prog).run()
    out = Batch.concat(sink_output("out"))
    # window [0,1s): person 1 matches auctions 10,11; window [1s,2s): person 3 -> 13
    pairs = sorted(zip(out.columns["pid"].tolist(),
                       out.columns["auction"].tolist()))
    assert pairs == [(1, 10), (1, 11), (3, 13)]


def test_join_with_expiration():
    t = lambda s: int(s * SEC)
    l = Batch(np.array([t(0.1)], dtype=np.int64),
              {"id": np.array([7], dtype=np.int64),
               "lv": np.array([100], dtype=np.int64)})
    r = Batch(np.array([t(0.2)], dtype=np.int64),
              {"id": np.array([7], dtype=np.int64),
               "rv": np.array([200], dtype=np.int64)})
    clear_sink("out")
    left = (Stream.source("memory", {"batches": [l]})
            .watermark(max_lateness_micros=0).key_by("id"))
    right = (Stream.source("memory", {"batches": [r]}, program=left.program)
             .watermark(max_lateness_micros=0).key_by("id"))
    prog = (left.join_with_expiration(right, 10 * SEC, 10 * SEC)
            .sink("memory", {"name": "out"}))
    LocalRunner(prog).run()
    out = Batch.concat(sink_output("out"))
    assert len(out) == 1
    assert int(out.columns["lv"][0]) == 100 and int(out.columns["rv"][0]) == 200


def test_non_window_aggregate(rng, monkeypatch):
    from arroyo_tpu.types import UPDATE_OP_COLUMN

    # refinement granularity is per input batch: input coalescing would
    # legitimately merge the two fragments into one create — disable it
    # so this test keeps pinning the create-then-update sequence
    monkeypatch.setenv("ARROYO_COALESCE", "0")
    ev1 = Batch(np.array([100, 200], dtype=np.int64),
                {"k": np.array([1, 1], dtype=np.int64),
                 "v": np.array([10, 20], dtype=np.int64)})
    ev2 = Batch(np.array([300], dtype=np.int64),
                {"k": np.array([1], dtype=np.int64),
                 "v": np.array([5], dtype=np.int64)})
    out = run_pipeline(
        [ev1, ev2],
        lambda s: s.key_by("k")
        .non_window_aggregate(60 * SEC, [AggSpec(AggKind.SUM, "v", "total")])
        .sink("memory", {"name": "out"}),
    )
    totals = out.columns["total"].tolist()
    ops = out.columns[UPDATE_OP_COLUMN].tolist()
    assert totals == [30.0, 35.0]
    assert ops == [0, 1]  # create then update


def test_out_of_order_within_lateness():
    """Events arriving out of order (within lateness) still land in the right
    windows — the watermark holds back by max_lateness."""
    ts = np.array([2 * SEC, SEC // 2, 3 * SEC, SEC + 100], dtype=np.int64)
    ev = Batch(ts, {"k": np.zeros(4, dtype=np.int64),
                    "v": np.ones(4, dtype=np.int64)})
    clear_sink("out")
    prog = (Stream.source("memory", {"batches": [ev]})
            .watermark(max_lateness_micros=4 * SEC)
            .key_by("k")
            .tumbling_aggregate(SEC, [AggSpec(AggKind.COUNT, None, "cnt")])
            .sink("memory", {"name": "out"}))
    LocalRunner(prog).run()
    out = Batch.concat(sink_output("out"))
    per_window = {int(out.columns["window_end"][i]): int(out.columns["cnt"][i])
                  for i in range(len(out))}
    assert per_window == {SEC: 1, 2 * SEC: 1, 3 * SEC: 1, 4 * SEC: 1}


def test_null_skipping_aggregates(rng):
    """Nulls (None in object columns -> NaN) are SKIPPED by SUM/MIN/MAX/AVG
    and by COUNT(col), and AVG divides by the NON-NULL row count — not the
    pane row count (reference nulls-skipping semantics,
    aggregating_window.rs; round-1 bug: avg used the shared pane count)."""
    n = 400
    ts = np.sort(rng.integers(0, 2 * SEC, n)).astype(np.int64)
    keys = rng.integers(0, 5, n).astype(np.int64)
    vals = rng.integers(1, 100, n).astype(np.int64)
    null_mask = rng.random(n) < 0.4
    col = np.array([None if m else int(v)
                    for v, m in zip(vals, null_mask)], dtype=object)
    ev = Batch(ts, {"k": keys, "v": col})
    aggs = [AggSpec(AggKind.COUNT, None, "cnt"),
            AggSpec(AggKind.COUNT, "v", "cnt_v"),
            AggSpec(AggKind.SUM, "v", "total"),
            AggSpec(AggKind.AVG, "v", "mean"),
            AggSpec(AggKind.MIN, "v", "lo"),
            AggSpec(AggKind.MAX, "v", "hi")]
    out = run_pipeline(
        [ev],
        lambda s: s.key_by("k").tumbling_aggregate(SEC, aggs)
        .sink("memory", {"name": "out"}),
    )
    assert out is not None
    # oracle over non-null rows per (key, window)
    exp = {}
    for t, k, v, m in zip(ts.tolist(), keys.tolist(), vals.tolist(),
                          null_mask.tolist()):
        e = (t // SEC + 1) * SEC
        c_all, c_v, s, mn, mx = exp.get((k, e), (0, 0, 0, None, None))
        c_all += 1
        if not m:
            c_v += 1
            s += v
            mn = v if mn is None else min(mn, v)
            mx = v if mx is None else max(mx, v)
        exp[(k, e)] = (c_all, c_v, s, mn, mx)
    seen = set()
    for i in range(len(out)):
        key = (int(out.columns["k"][i]), int(out.columns["window_end"][i]))
        c_all, c_v, s, mn, mx = exp[key]
        seen.add(key)
        assert int(out.columns["cnt"][i]) == c_all
        assert int(out.columns["cnt_v"][i]) == c_v
        if c_v == 0:  # all-null pane: every column agg is NULL (NaN)
            for c in ("total", "mean", "lo", "hi"):
                assert np.isnan(out.columns[c][i]), (key, c)
        else:
            assert int(out.columns["total"][i]) == s
            assert out.columns["mean"][i] == pytest.approx(s / c_v, rel=1e-5)
            assert int(out.columns["lo"][i]) == mn
            assert int(out.columns["hi"][i]) == mx
    assert seen == set(exp)


def test_sum_exactness_hot_key_large_magnitudes(rng):
    """Numeric-fidelity policy (keyed_bins.ACC_DTYPE): SUM of int64 prices
    over a hot key must equal the exact integer oracle even when the
    per-cell magnitude passes 2^24 (where f32 accumulators drift — the
    reference aggregates in exact i64, aggregating_window.rs).  500k rows
    into ONE (key, bin) cell with values ~10^6 sums to ~5*10^11 >> 2^24."""
    from arroyo_tpu.ops.keyed_bins import KeyedBinState
    from arroyo_tpu.graph.logical import AggKind, AggSpec

    n = 500_000
    ts = rng.integers(0, SEC, n).astype(np.int64)  # all in one bin
    keys = np.zeros(n, dtype=np.int64)  # one hot key
    vals = rng.integers(1_000_000, 2_000_000, n).astype(np.int64)
    from arroyo_tpu.types import hash_columns

    kh = hash_columns([keys])
    aggs = (AggSpec(AggKind.SUM, "v", "total"),
            AggSpec(AggKind.COUNT, None, "cnt"),
            AggSpec(AggKind.AVG, "v", "mean"))
    st = KeyedBinState(aggs, SEC, SEC, capacity=16)
    # feed in chunks so cross-batch accumulation is exercised too
    for s in range(0, n, 50_000):
        e = s + 50_000
        st.update(kh[s:e], ts[s:e], {"v": vals[s:e]})
    f = st.fire_panes(1 << 60, final=True)
    assert f is not None
    _kk, oc, _wend, _cnt = f
    exact = int(vals.sum())  # ~7.5e11, exact in int64 and in f64 < 2^53
    assert int(oc["total"][0]) == exact
    assert int(oc["cnt"][0]) == n
    assert oc["mean"][0] == pytest.approx(exact / n, rel=1e-12)


def test_mesh_sum_exactness_hot_key(rng):
    """Same exactness pin for the mesh-sharded state."""
    from arroyo_tpu.parallel.mesh_window import MeshKeyedBinState
    from arroyo_tpu.graph.logical import AggKind, AggSpec
    from arroyo_tpu.types import hash_columns

    n = 200_000
    ts = rng.integers(0, SEC, n).astype(np.int64)
    keys = np.zeros(n, dtype=np.int64)
    vals = rng.integers(1_000_000, 2_000_000, n).astype(np.int64)
    kh = hash_columns([keys])
    aggs = (AggSpec(AggKind.SUM, "v", "total"),)
    st = MeshKeyedBinState(aggs, SEC, SEC, capacity=16, n_shards=4)
    for s in range(0, n, 50_000):
        e = s + 50_000
        st._lookup_or_insert(kh[s:e])
        st.update(kh[s:e], ts[s:e], {"v": vals[s:e]})
    f = st.fire_panes(1 << 60, final=True)
    assert f is not None
    _kk, oc, _wend, _cnt = f
    assert int(oc["total"][0]) == int(vals.sum())


def test_ring_growth_does_not_ghost_duplicate(rng):
    """Two interleaved streams with far-apart time bases (e.g. impulse
    splits whose wall-clock bases drifted during jit compiles) force a
    mid-stream ring growth: growing must NOT replicate old ring slots
    into the newly-spanned bin range.  Regression for the ghost
    duplication where _grow_ring copied [min, max] AFTER the new batch
    had already extended the bounds."""
    from arroyo_tpu.graph.logical import AggKind, AggSpec
    from arroyo_tpu.ops.keyed_bins import KeyedBinState
    from arroyo_tpu.types import hash_columns

    aggs = (AggSpec(AggKind.COUNT, None, "cnt"),
            AggSpec(AggKind.SUM, "v", "total"))
    nA = nB = 2000
    tsA = np.sort(rng.integers(0, 120_000, nA)).astype(np.int64)
    tsB = np.sort(rng.integers(1_500_000, 1_620_000, nB)).astype(np.int64)
    kA = rng.integers(0, 4, nA).astype(np.int64)
    kB = rng.integers(0, 4, nB).astype(np.int64)
    vA = rng.integers(1, 100, nA).astype(np.int64)
    vB = rng.integers(1, 100, nB).astype(np.int64)
    khA, khB = hash_columns([kA]), hash_columns([kB])

    exp = {}
    for ts, kh, vv in ((tsA, khA, vA), (tsB, khB, vB)):
        for t, k, v in zip(ts.tolist(), kh.tolist(), vv.tolist()):
            b = t // 100_000
            for e in (b, b + 1):  # W/slide = 2 panes per event
                c, s = exp.get((k, e), (0, 0))
                exp[(k, e)] = (c + 1, s + v)

    st = KeyedBinState(aggs, 100_000, 200_000, capacity=16)
    got = {}

    def fire(wm, final=False):
        f = st.fire_panes(wm, final=final)
        if f:
            kk, oc, wend, _ = f
            for j in range(len(kk)):
                key = (int(kk[j]), int(wend[j]) // 100_000 - 1)
                assert key not in got, f"pane refire {key}"
                got[key] = (int(oc["cnt"][j]), int(oc["total"][j]))

    stepsA = np.array_split(np.arange(nA), 4)
    stepsB = np.array_split(np.arange(nB), 4)
    for ia, ib in zip(stepsA, stepsB):
        st.update(khA[ia], tsA[ia], {"v": vA[ia]})
        st.update(khB[ib], tsB[ib], {"v": vB[ib]})
        fire(int(min(tsA[ia[-1]], tsB[ib[0]])))
    fire(1 << 60, final=True)
    assert got == exp


def test_min_max_beyond_float32_range():
    """MIN/MAX null identities are f64 extremes: values beyond the f32
    range (+/-3.4e38) must survive both aggregation paths instead of
    clipping to the identity."""
    from arroyo_tpu.graph.logical import AggKind, AggSpec
    from arroyo_tpu.ops.keyed_bins import KeyedBinState
    from arroyo_tpu.ops.segment import segment_aggregate
    from arroyo_tpu.types import hash_columns

    vals = np.array([-1e300, 1e300, np.nan], dtype=np.float64)
    ts = np.array([100, 200, 300], dtype=np.int64)
    kh = hash_columns([np.zeros(3, dtype=np.int64)])
    aggs = (AggSpec(AggKind.MIN, "v", "lo"), AggSpec(AggKind.MAX, "v", "hi"))

    st = KeyedBinState(aggs, SEC, SEC, capacity=16)
    st.update(kh, ts, {"v": vals})
    _k, oc, _w, _c = st.fire_panes(1 << 60, final=True)
    assert oc["lo"][0] == -1e300 and oc["hi"][0] == 1e300

    _u, cols, _t, _rc, _vc = segment_aggregate(kh, ts, {"v": vals}, aggs)
    assert cols["lo"][0] == -1e300 and cols["hi"][0] == 1e300


def test_segment_aggregate_host_branch_parity(rng, monkeypatch):
    """The tunnel-regime numpy-reduceat branch of segment_aggregate
    (ops/segment._segment_host) must match the device kernel on every
    channel kind — sums to f64 association tolerance, min/max/count
    exactly — including null skipping and all-null segments."""
    from arroyo_tpu.graph.logical import AggKind, AggSpec
    from arroyo_tpu.ops.segment import segment_aggregate

    n = 4000
    kh = rng.integers(0, 60, n).astype(np.uint64)
    ts = rng.integers(0, 10**7, n).astype(np.int64)
    v = rng.standard_normal(n)
    v[rng.random(n) < 0.15] = np.nan
    v[kh == kh.min()] = np.nan  # one all-null segment
    aggs = (AggSpec(AggKind.SUM, "v", "s"), AggSpec(AggKind.MIN, "v", "mn"),
            AggSpec(AggKind.MAX, "v", "mx"),
            AggSpec(AggKind.COUNT, None, "c"),
            AggSpec(AggKind.AVG, "v", "a"),
            AggSpec(AggKind.COUNT, "v", "cv"))
    monkeypatch.setenv("ARROYO_SEGMENT_HOST", "0")
    dev = segment_aggregate(kh, ts, {"v": v}, aggs)
    monkeypatch.setenv("ARROYO_SEGMENT_HOST", "1")
    host = segment_aggregate(kh, ts, {"v": v}, aggs)
    np.testing.assert_array_equal(dev[0], host[0])
    for k in ("s", "a"):
        np.testing.assert_allclose(dev[1][k], host[1][k], rtol=1e-12,
                                   equal_nan=True, err_msg=k)
    for k in ("mn", "mx", "c", "cv"):
        np.testing.assert_array_equal(dev[1][k], host[1][k], err_msg=k)
    np.testing.assert_array_equal(dev[3], host[3])
    for k in dev[4]:
        np.testing.assert_array_equal(dev[4][k], host[4][k], err_msg=k)


def test_apply_top_n_host_device_boundary_parity(rng):
    """_apply_top_n routes to the device segment_top_k only at >= 512
    rows: the kept-row set AND the materialized rank column must agree
    across the boundary (same data, padded to cross it)."""
    from arroyo_tpu.engine.operators_window import _apply_top_n

    n = 511
    part = rng.integers(0, 23, n).astype(np.int64)
    vals = rng.integers(0, 40, n).astype(np.int64)  # ties included

    def run(nn):
        b = Batch(np.zeros(nn, dtype=np.int64),
                  {"p": part[:nn] if nn <= n else np.concatenate(
                      [part, part[:nn - n]]),
                   "v": vals[:nn] if nn <= n else np.concatenate(
                      [vals, vals[:nn - n]])})
        out = _apply_top_n(b, ("p",), "v", 3, rank_column="rn")
        return out

    # host path (511) vs device path (512: one duplicated row appended)
    host = run(511)
    dev = run(512)
    def canon(o, limit):
        return sorted(zip(o.columns["p"].tolist()[:limit],
                          o.columns["v"].tolist()[:limit],
                          o.columns["rn"].tolist()[:limit]))
    # the appended row can displace at most itself; compare the common
    # prefix semantics: per-partition (value, rank) multisets must agree
    # for partitions untouched by the duplicate
    dup_part = int(part[0])
    hrows = [(p, v, r) for p, v, r in canon(host, len(host))
             if p != dup_part]
    drows = [(p, v, r) for p, v, r in canon(dev, len(dev))
             if p != dup_part]
    assert hrows == drows
    assert set(host.columns["rn"].tolist()) <= {1, 2, 3}
    assert set(dev.columns["rn"].tolist()) <= {1, 2, 3}


def test_device_topk_matches_host_lexsort(rng):
    """ops/topk.segment_top_k == the host lexsort rank-per-partition, at
    sizes crossing the device-dispatch threshold, with ties."""
    from arroyo_tpu.ops.topk import segment_top_k

    for n, k in [(700, 3), (4096, 5), (513, 1)]:
        part = rng.integers(0, 37, n).astype(np.int64)
        vals = rng.integers(0, 50, n).astype(np.int64)  # plenty of ties
        got = segment_top_k(part, vals, k)
        order = np.lexsort((-vals.astype(np.float64), part))
        ps = part[order]
        is_start = np.ones(n, dtype=bool)
        is_start[1:] = ps[1:] != ps[:-1]
        seg_id = np.cumsum(is_start) - 1
        rank = np.arange(n) - is_start.nonzero()[0][seg_id]
        exp = np.sort(order[rank < k])
        np.testing.assert_array_equal(got, exp)


@pytest.mark.parametrize("probe", ["search", "merged"])
def test_device_join_pairs_matches_host(rng, monkeypatch, probe):
    """ops/join.join_pairs: the device sort/probe/expand kernels must
    produce exactly the host fallback's (lo, ro, lidx, ridx, counts) —
    including multi-match fan-out, empty intersections, and sizes
    crossing the pad buckets — on both the searchsorted probe and the
    TPU merged-rank probe (ops/join._merged_probe)."""
    from arroyo_tpu.ops import join as dj

    monkeypatch.setenv("ARROYO_JOIN_PROBE", probe)
    for nl, nr, span in [(5, 7, 4), (600, 300, 50), (2048, 4096, 130),
                         (1000, 1, 9), (1, 1000, 9)]:
        lk = rng.integers(0, span, nl).astype(np.uint64)
        rk = rng.integers(0, span, nr).astype(np.uint64)
        if span == 130:
            # exercise the hi/lo word split: keys above 2^32 whose low
            # words collide across distinct high words
            hi = rng.integers(0, 3, nl).astype(np.uint64) << np.uint64(32)
            lk = lk | hi
            rk = rk | (rng.integers(0, 3, nr).astype(np.uint64)
                       << np.uint64(32))
        monkeypatch.setenv("ARROYO_DEVICE_JOIN", "off")
        h = dj.join_pairs(lk, rk)
        monkeypatch.setenv("ARROYO_DEVICE_JOIN", "on")
        d = dj.join_pairs(lk, rk)
        for name, hv, dv in zip(("lo", "ro", "lidx", "ridx", "counts"),
                                h, d):
            np.testing.assert_array_equal(hv, dv, err_msg=f"{name} "
                                          f"nl={nl} nr={nr}")


def test_device_join_sentinel_collision_falls_back(monkeypatch):
    """A real key equal to the pad sentinel routes to the host path and
    still joins correctly."""
    from arroyo_tpu.ops import join as dj

    monkeypatch.setenv("ARROYO_DEVICE_JOIN", "on")
    lk = np.array([3, dj.SENTINEL, 5], dtype=np.uint64)
    rk = np.array([dj.SENTINEL, 5], dtype=np.uint64)
    lo, ro, lidx, ridx, counts = dj.join_pairs(lk, rk)
    pairs = {(int(lk[lo[i]]), int(rk[ro[j]]))
             for i, j in zip(lidx.tolist(), ridx.tolist())}
    assert pairs == {(int(dj.SENTINEL), int(dj.SENTINEL)), (5, 5)}


def test_i32_counts_plane_promotes_to_i64(monkeypatch):
    """COUNT(*) reads the i32 counts plane directly (no f64 channel rides
    the transfer), so once total ingested rows could wrap an i32 cell or
    pane sum the plane must promote to i64 — otherwise a hot key wraps to
    a negative count (code-review r4 finding)."""
    import jax.numpy as jnp

    from arroyo_tpu.ops.keyed_bins import KeyedBinState

    monkeypatch.setattr(KeyedBinState, "_i32_promote", 600)
    aggs = (AggSpec(kind=AggKind.COUNT, column=None, output="n"),)
    st = KeyedBinState(aggs, slide_micros=1000, width_micros=1000,
                       capacity=16)
    rng = np.random.default_rng(3)
    total = 0
    for _ in range(5):
        n = 200
        keys = rng.integers(0, 3, n).astype(np.uint64)
        ts = np.zeros(n, dtype=np.int64)  # one bin, one hot pane
        st.update(keys, ts, {})
        total += n
    assert st.counts.dtype == jnp.int64  # crossed the promotion threshold
    # total_rows survives a checkpoint round-trip (snapshot before the
    # final fire: firing evicts the bins, legitimately zeroing the mass)
    st2 = KeyedBinState(aggs, 1000, 1000, capacity=16)
    st2.restore(st.snapshot())
    assert st2.total_rows == total
    keys_o, cols, wend, cnts = st.fire_panes(10**9, final=True)
    assert int(cols["n"].sum()) == total  # every row counted, no wrap
    # ring emission follows the promoted dtype instead of recasting i32
    monkeypatch.setenv("ARROYO_RING", "on")
    st3 = KeyedBinState(aggs, 1000, 1000, capacity=16)
    st3.restore(st2.snapshot())
    assert st3.counts.dtype == jnp.int64
    k3, c3, w3, n3 = st3.fire_panes(10**9, final=True)
    assert n3.dtype == np.int64
    assert int(c3["n"].sum()) == total


def test_count_star_skips_f64_transfer():
    """A bare COUNT(*) query ships no f64 emit channels at all — the
    aggregate IS the counts plane (tunnel-transfer optimization); mixed
    aggs keep their channels and stay correct alongside it."""
    from arroyo_tpu.ops.keyed_bins import KeyedBinState

    aggs = (AggSpec(kind=AggKind.COUNT, column=None, output="n"),
            AggSpec(kind=AggKind.SUM, column="v", output="s"))
    st = KeyedBinState(aggs, slide_micros=1000, width_micros=2000,
                       capacity=16)
    assert st._dup_ch == (0,)
    # channels that ride the transfer: SUM + its validity, not COUNT(*)
    assert st._ch_kinds[st._xfer_ch[0]] == "sum"
    rng = np.random.default_rng(4)
    n = 500
    keys = rng.integers(0, 5, n).astype(np.uint64)
    ts = rng.integers(0, 5000, n).astype(np.int64)
    v = rng.normal(size=n)
    st.update(keys, ts, {"v": v})
    keys_o, cols, wend, cnts = st.fire_panes(10**9, final=True)
    assert int(cols["n"].sum()) == 2 * n  # each row in W=2 panes
    np.testing.assert_array_equal(cols["n"], cnts)  # COUNT(*) == row count
    oracle = {}
    for k, t, vv in zip(keys, ts, v):
        b = t // 1000
        for pane in range(b, b + 2):
            key = (int(k), int((pane + 1) * 1000))
            c, s = oracle.get(key, (0, 0.0))
            oracle[key] = (c + 1, s + vv)
    for i in range(len(keys_o)):
        c, s = oracle[(int(keys_o[i]), int(wend[i]))]
        assert cols["n"][i] == c
        assert np.isclose(cols["s"][i], s, rtol=1e-12)


def test_compact_emission_matches_dense(monkeypatch):
    """Device-compacted emission (two-phase nnz + gather) returns exactly
    the dense path's rows, in the same row-major order, for every agg
    kind incl. null-skipping AVG."""
    from arroyo_tpu.ops.keyed_bins import KeyedBinState

    aggs = (AggSpec(kind=AggKind.COUNT, column=None, output="n"),
            AggSpec(kind=AggKind.SUM, column="v", output="s"),
            AggSpec(kind=AggKind.AVG, column="w", output="a"),
            AggSpec(kind=AggKind.MIN, column="v", output="mn"))
    rng = np.random.default_rng(11)
    n = 4000
    keys = rng.integers(0, 50, n).astype(np.uint64)
    ts = rng.integers(0, 9000, n).astype(np.int64)
    v = rng.normal(size=n)
    w = rng.normal(size=n)
    w[rng.random(n) < 0.4] = np.nan

    def run(mode):
        monkeypatch.setenv("ARROYO_EMIT_COMPACT", mode)
        st = KeyedBinState(aggs, slide_micros=1000, width_micros=4000,
                           capacity=64)
        out = []
        for i in range(0, n, 800):
            sl = slice(i, i + 800)
            st.update(keys[sl], ts[sl], {"v": v[sl], "w": w[sl]})
            r = st.fire_panes(int(ts[sl].max()))  # mid-stream fires too
            if r is not None:
                out.append(r)
        r = st.fire_panes(10 ** 9, final=True)
        if r is not None:
            out.append(r)
        return out

    dense = run("off")
    comp = run("on")
    assert len(dense) == len(comp)
    for (k1, c1, w1, n1), (k2, c2, w2, n2) in zip(dense, comp):
        np.testing.assert_array_equal(k1, k2)
        np.testing.assert_array_equal(w1, w2)
        np.testing.assert_array_equal(n1, n2)
        for name in ("n", "s", "a", "mn"):
            np.testing.assert_allclose(c1[name].astype(float),
                                       c2[name].astype(float),
                                       rtol=1e-12, atol=1e-15)


def test_cnt16_bound_survives_restore():
    """The u16 emit-downcast proof (W * _cell_bound < 65000) must not be
    vacuously true after restore: 70k rows in one (key, bin) cell wrapped
    COUNT(*) to 70000 % 65536 = 4464 through a checkpoint round-trip
    (code-review r4 finding, live repro)."""
    from arroyo_tpu.ops.keyed_bins import KeyedBinState

    aggs = (AggSpec(kind=AggKind.COUNT, column=None, output="n"),)
    st = KeyedBinState(aggs, slide_micros=1000, width_micros=1000,
                       capacity=16)
    n = 70_000
    st.update(np.full(n, 5, np.uint64), np.zeros(n, np.int64), {})
    st2 = KeyedBinState(aggs, 1000, 1000, capacity=16)
    st2.restore(st.snapshot())
    assert max(st2._bin_bound.values()) >= n  # proof sees restored mass
    keys_o, cols, wend, cnts = st2.fire_panes(10 ** 9, final=True)
    assert int(cols["n"][0]) == n  # not n % 65536


def test_group_by_window_flush_is_idempotent():
    """A record re-created for an already-released window (late panes —
    e.g. a racing upstream) must NOT emit a second final row: q5's join
    would match the stale partial max and duplicate output rows
    (observed once as a 6th q5 row on a cold-compile run)."""
    from arroyo_tpu.engine.operators_window import NonWindowAggOperator
    from arroyo_tpu.state.store import StateStore
    from arroyo_tpu.types import TaskInfo

    class Ctx:
        def __init__(self, store, last_watermark=None):
            self.state = store
            self.last_watermark = last_watermark
            self.out = []

        async def collect(self, batch):
            self.out.append(batch)

        async def broadcast(self, msg):
            pass

    op = NonWindowAggOperator(
        "max_per_window", 86_400_000_000,
        (AggSpec(AggKind.MAX, "num", "maxn"),), flush_key="window_end")
    store = StateStore.new_in_memory(
        TaskInfo("job", "op", "max_per_window", 0, 1))
    ctx = Ctx(store)

    async def drive():
        await op.on_start(ctx)
        wend = 10_000_000
        b1 = Batch(np.array([wend - 1, wend - 1], dtype=np.int64),
                   {"window_end": np.array([wend, wend], dtype=np.int64),
                    "num": np.array([5, 7], dtype=np.int64)},
                   np.array([1, 1], dtype=np.uint64), ("window_end",))
        await op.process_batch(b1, ctx)
        await op.handle_watermark(wend, ctx)  # releases the window
        assert len(ctx.out) == 1
        assert int(ctx.out[0].columns["maxn"][0]) == 7
        # late re-creation: more rows for the SAME window after release
        b2 = Batch(np.array([wend - 1], dtype=np.int64),
                   {"window_end": np.array([wend], dtype=np.int64),
                    "num": np.array([7], dtype=np.int64)},
                   np.array([1], dtype=np.uint64), ("window_end",))
        await op.process_batch(b2, ctx)
        await op.handle_watermark(wend + 2_000_000, ctx)
        assert len(ctx.out) == 1, "late re-creation must not re-emit"

        # the guard survives a checkpoint restore: a fresh operator whose
        # context restores at watermark `wend` must also drop the late
        # re-creation instead of emitting a duplicate final row
        op2 = NonWindowAggOperator(
            "max_per_window", 86_400_000_000,
            (AggSpec(AggKind.MAX, "num", "maxn"),), flush_key="window_end")
        ctx2 = Ctx(StateStore.new_in_memory(
            TaskInfo("job", "op", "max_per_window", 0, 1)),
            last_watermark=wend)
        await op2.on_start(ctx2)
        await op2.process_batch(b2, ctx2)
        await op2.handle_watermark(wend + 2_000_000, ctx2)
        assert len(ctx2.out) == 0, "restored guard must drop late windows"

    asyncio.run(drive())


def test_window_argmax_skips_null_values():
    """SQL NULL (NaN) values never equal the join's max — one all-null
    aggregate row must not poison the window extremum and drop every
    row (pre-fix: vals.max() returned NaN and nothing matched)."""
    from arroyo_tpu.engine.operators_window import WindowArgmaxOperator
    from arroyo_tpu.state.store import StateStore
    from arroyo_tpu.types import TaskInfo

    class Ctx:
        def __init__(self, store):
            self.state = store
            self.last_watermark = None
            self.out = []
            self.timers = self

        def schedule(self, t, key):
            self._timer = (t, key)

        async def collect(self, batch):
            self.out.append(batch)

        async def broadcast(self, msg):
            pass

    op = WindowArgmaxOperator("am", "num", "max",
                              (("mx", "num"),), 1_000_000)
    ctx = Ctx(StateStore.new_in_memory(TaskInfo("j", "o", "am", 0, 1)))

    async def drive():
        await op.on_start(ctx)
        wend = 1_000_000
        b = Batch(np.full(3, wend - 1, np.int64),
                  {"window_end": np.full(3, wend, np.int64),
                   "k": np.array([1, 2, 3], np.int64),
                   "num": np.array([5.0, np.nan, 7.0])},
                  np.array([9, 9, 9], np.uint64), ("window_end",))
        await op.process_batch(b, ctx)
        await op.handle_timer(wend, ("am", wend), None, ctx)
        assert len(ctx.out) == 1
        out = ctx.out[0]
        assert out.columns["k"].tolist() == [3]  # the non-null max row
        assert out.columns["num"].tolist() == [7.0]
        assert out.columns["mx"].tolist() == [7.0]

        # an ALL-null window emits nothing (no row can equal the max)
        wend2 = 2_000_000
        b2 = Batch(np.full(2, wend2 - 1, np.int64),
                   {"window_end": np.full(2, wend2, np.int64),
                    "k": np.array([1, 2], np.int64),
                    "num": np.array([np.nan, np.nan])},
                   np.array([9, 9], np.uint64), ("window_end",))
        await op.process_batch(b2, ctx)
        await op.handle_timer(wend2, ("am", wend2), None, ctx)
        assert len(ctx.out) == 1  # nothing new

    asyncio.run(drive())


def test_window_argmax_raw_restore_late_rows():
    """Raw mode across a (simulated) restore: the released-window guard
    re-arms from the checkpoint watermark and late rows match the
    PERSISTED final extrema — a late tying row emits exactly as the
    TTL'd join it replaces would, a non-tying or unknown-window late
    row drops, and the released window never re-fires wholesale."""
    from arroyo_tpu.engine.operators_window import WindowArgmaxOperator
    from arroyo_tpu.state.store import StateStore
    from arroyo_tpu.types import TaskInfo

    class Ctx:
        def __init__(self, store, last_watermark=None):
            self.state = store
            self.last_watermark = last_watermark
            self.out = []
            self.timers = self

        def schedule(self, t, key):
            pass

        async def collect(self, batch):
            self.out.append(batch)

    W = 1_000_000
    store = StateStore.new_in_memory(TaskInfo("j", "o", "am", 0, 1))

    def make_op():
        return WindowArgmaxOperator("am", "v", "max", (("mx", "v"),), W,
                                    raw=True, late_ttl_micros=3600 * W)

    def rows(wend, vals, keys):
        n = len(vals)
        return Batch(np.full(n, wend - 1, np.int64),
                     {"window_end": np.full(n, wend, np.int64),
                      "window_start": np.full(n, wend - W, np.int64),
                      "k": np.asarray(keys, np.int64),
                      "v": np.asarray(vals, float)},
                     np.full(n, 9, np.uint64), ("window_end",))

    async def drive():
        op1 = make_op()
        ctx1 = Ctx(store)
        await op1.on_start(ctx1)
        await op1.process_batch(rows(W, [9.0, 3.0], [1, 2]), ctx1)
        await op1.handle_timer(W, ("am", W), None, ctx1)
        assert len(ctx1.out) == 1
        assert ctx1.out[0].columns["k"].tolist() == [1]

        # "restore": fresh operator over the same state, checkpoint
        # watermark at the released window end
        op2 = make_op()
        ctx2 = Ctx(store, last_watermark=W)
        await op2.on_start(ctx2)
        # late batch: a tie (emits via the persisted final), a dominated
        # value (drops), and an unknown released window (drops)
        await op2.process_batch(rows(W, [9.0, 8.0], [3, 5]), ctx2)
        assert len(ctx2.out) == 1
        out = ctx2.out[0]
        assert out.columns["k"].tolist() == [3]
        assert out.columns["mx"].tolist() == [9.0]
        await op2.process_batch(rows(W // 2, [4.0], [7]), ctx2)
        assert len(ctx2.out) == 1  # nothing new, window never existed

    asyncio.run(drive())
