"""Real multi-process cluster: ProcessScheduler spawns worker OS
processes (schedulers/mod.rs:77-233 analog); the controller drives them
over gRPC and data crosses process boundaries on the TCP shuffle plane.
"""

import asyncio
import json

import pytest

from arroyo_tpu import Stream
from arroyo_tpu.controller.controller import ControllerServer
from arroyo_tpu.controller.scheduler import ProcessScheduler
from arroyo_tpu.controller.state_machine import JobState
from arroyo_tpu.graph.logical import AggKind, AggSpec



def test_process_cluster_pipeline(tmp_path):
    out_path = tmp_path / "out.jsonl"

    async def scenario():
        sched = ProcessScheduler()
        ctrl = ControllerServer(sched)
        await ctrl.start()
        prog = (
            Stream.source("impulse", {"event_rate": 0.0,
                                      "message_count": 3000,
                                      "event_time_interval_micros": 1000,
                                      "batch_size": 128}, parallelism=2)
            .watermark(max_lateness_micros=0)
            .map(lambda c: {"counter": c["counter"],
                            "bucket": c["counter"] % 7}, name="b")
            .key_by("bucket")
            .tumbling_aggregate(
                300 * 1000, [AggSpec(AggKind.COUNT, None, "cnt")],
                parallelism=2)
            .sink("single_file", {"path": str(out_path)}, parallelism=1)
        )
        job_id = await ctrl.submit_job(
            prog, checkpoint_url=f"file://{tmp_path}/ckpt", n_workers=2)
        try:
            # two real OS processes must register as workers
            for _ in range(300):
                if len(ctrl.jobs[job_id].workers) >= 2:
                    break
                await asyncio.sleep(0.1)
            assert len(ctrl.jobs[job_id].workers) >= 2, "workers never came"
            pids = sched.workers_for_job(job_id)
            assert len(pids) == 2 and all(p.startswith("pid-")
                                          for p in pids)
            state = await ctrl.wait_for_state(job_id, JobState.FINISHED,
                                              timeout=120)
        finally:
            await sched.stop_workers(job_id)
            await ctrl.stop()
        return state

    state = asyncio.run(scenario())
    assert state == JobState.FINISHED
    rows = [json.loads(line) for line in open(out_path)]
    assert sum(r["cnt"] for r in rows) == 3000
    assert len({r["bucket"] for r in rows}) == 7



def test_process_scheduler_stop_kills_workers(tmp_path):
    async def scenario():
        sched = ProcessScheduler()
        ctrl = ControllerServer(sched)
        await ctrl.start()
        prog = (
            Stream.source("impulse", {"event_rate": 50.0,
                                      "message_count": 10_000_000,
                                      "batch_size": 64})
            .map(lambda c: {"counter": c["counter"]}, name="m")
            .sink("blackhole", {})
        )
        job_id = await ctrl.submit_job(
            prog, checkpoint_url=f"file://{tmp_path}/ckpt", n_workers=1)
        await ctrl.wait_for_state(job_id, JobState.RUNNING, timeout=60)
        assert len(sched.workers_for_job(job_id)) == 1
        await sched.stop_workers(job_id, force=True)
        assert sched.workers_for_job(job_id) == []
        await ctrl.stop()

    asyncio.run(scenario())


def test_worker_kill_mid_run_recovers_exactly_once(tmp_path, monkeypatch):
    """Fault injection the reference lacks: SIGKILL a real worker process
    mid-stream; the controller must detect the dead worker, restart the
    job from the last checkpoint, and the output must be exactly-once."""
    import os
    import signal

    monkeypatch.setenv("HEARTBEAT_INTERVAL_SECS", "0.3")
    monkeypatch.setenv("HEARTBEAT_TIMEOUT_SECS", "2.0")
    monkeypatch.setenv("CHECKPOINT_INTERVAL_SECS", "0.5")
    from arroyo_tpu.config import reset_config

    reset_config()
    out_path = tmp_path / "out.jsonl"
    N = 40_000

    async def scenario():
        sched = ProcessScheduler()
        ctrl = ControllerServer(sched)
        await ctrl.start()
        prog = (
            Stream.source("impulse", {"event_rate": 8000.0,
                                      "message_count": N,
                                      "event_time_interval_micros": 1000,
                                      "batch_size": 256})
            .watermark(max_lateness_micros=0)
            .map(lambda c: {"counter": c["counter"],
                            "bucket": c["counter"] % 5}, name="b")
            .key_by("bucket")
            .tumbling_aggregate(
                500 * 1000, [AggSpec(AggKind.COUNT, None, "cnt")])
            .sink("single_file", {"path": str(out_path)})
        )
        job_id = await ctrl.submit_job(
            prog, checkpoint_url=f"file://{tmp_path}/ckpt", n_workers=1)
        try:
            # wait until at least one checkpoint has completed
            for _ in range(600):
                if (ctrl.jobs[job_id].last_successful_epoch or 0) >= 1:
                    break
                await asyncio.sleep(0.05)
            assert (ctrl.jobs[job_id].last_successful_epoch or 0) >= 1

            # SIGKILL the worker process, mid-stream
            [pid_s] = sched.workers_for_job(job_id)
            os.kill(int(pid_s.split("-", 1)[1]), signal.SIGKILL)

            state = await ctrl.wait_for_state(job_id, JobState.FINISHED,
                                              timeout=120)
        finally:
            await sched.stop_workers(job_id)
            await ctrl.stop()
        return state

    try:
        state = asyncio.run(scenario())
    finally:
        # drop the cached fast-heartbeat config so later tests re-read the
        # (restored) env
        reset_config()
    assert state == JobState.FINISHED
    rows = [json.loads(line) for line in open(out_path)]
    assert sum(r["cnt"] for r in rows) == N  # exactly-once across the kill


@pytest.mark.slow
def test_mesh_sharded_state_inside_cluster_worker(tmp_path, monkeypatch):
    """A real TPU pod is one worker x many chips: run the mesh-sharded
    BinAgg state INSIDE a process-cluster worker (ARROYO_MESH=8 over the
    8-device CPU mesh the worker inherits), checkpoint mid-stream, SIGKILL
    the worker, and recover — exactly-once output AND the checkpoint must
    provably have been written by the 8-shard mesh state."""
    import os
    import signal

    import numpy as np

    monkeypatch.setenv("ARROYO_MESH", "8")  # inherited by the worker proc
    monkeypatch.setenv("HEARTBEAT_INTERVAL_SECS", "0.3")
    monkeypatch.setenv("HEARTBEAT_TIMEOUT_SECS", "2.0")
    monkeypatch.setenv("CHECKPOINT_INTERVAL_SECS", "0.5")
    from arroyo_tpu.config import reset_config

    reset_config()
    out_path = tmp_path / "out.jsonl"
    N = 30_000

    async def scenario():
        sched = ProcessScheduler()
        ctrl = ControllerServer(sched)
        await ctrl.start()
        prog = (
            Stream.source("impulse", {"event_rate": 8000.0,
                                      "message_count": N,
                                      "event_time_interval_micros": 1000,
                                      "batch_size": 256})
            .watermark(max_lateness_micros=0)
            .map(lambda c: {"counter": c["counter"],
                            "bucket": c["counter"] % 5}, name="b")
            .key_by("bucket")
            .sliding_aggregate(
                500 * 1000, 250 * 1000,
                [AggSpec(AggKind.COUNT, None, "cnt")])
            .sink("single_file", {"path": str(out_path)})
        )
        job_id = await ctrl.submit_job(
            prog, checkpoint_url=f"file://{tmp_path}/ckpt", n_workers=1)
        try:
            for _ in range(600):
                if (ctrl.jobs[job_id].last_successful_epoch or 0) >= 1:
                    break
                await asyncio.sleep(0.05)
            assert (ctrl.jobs[job_id].last_successful_epoch or 0) >= 1

            [pid_s] = sched.workers_for_job(job_id)
            os.kill(int(pid_s.split("-", 1)[1]), signal.SIGKILL)

            state = await ctrl.wait_for_state(job_id, JobState.FINISHED,
                                              timeout=120)
        finally:
            await sched.stop_workers(job_id)
            await ctrl.stop()
        return state

    try:
        state = asyncio.run(scenario())
    finally:
        reset_config()
    assert state == JobState.FINISHED

    # exactly-once: every sliding pane counted, no pane twice.  Each event
    # feeds width/slide = 2 panes.
    rows = [json.loads(line) for line in open(out_path)]
    assert sum(r["cnt"] for r in rows) == 2 * N
    assert len({r["bucket"] for r in rows}) == 5

    # the checkpoint must carry the mesh provenance marker: the device
    # table snapshot was written by the 8-shard MeshKeyedBinState (the
    # canonical format stores arrays as __array__<name> rows)
    import io

    import pyarrow.parquet as pq

    shards_seen = set()
    for root, _dirs, files in os.walk(tmp_path / "ckpt"):
        for f in files:
            if not f.endswith(".parquet"):
                continue
            table = pq.read_table(os.path.join(root, f))
            for key, val in zip(table.column("key").to_pylist(),
                                table.column("value").to_pylist()):
                if bytes(key) == b"__array__mesh_shards":
                    arr = np.load(io.BytesIO(bytes(val)),
                                  allow_pickle=True)
                    shards_seen.add(int(arr[0]))
    assert 8 in shards_seen, (
        f"no 8-shard mesh checkpoint found (saw {shards_seen})")


@pytest.mark.slow
def test_controller_crash_resumes_job_from_durable_store(tmp_path, monkeypatch):
    """Durable controller (states/mod.rs:577-628 analog): submit a
    checkpointing job, CRASH the controller (no graceful stop — workers
    orphaned), start a fresh controller on the same sqlite store: it must
    reap the orphans, re-adopt the job, return it to Running, and finish
    with exactly-once output from the last checkpoint."""
    import os

    monkeypatch.setenv("HEARTBEAT_INTERVAL_SECS", "0.3")
    monkeypatch.setenv("HEARTBEAT_TIMEOUT_SECS", "2.0")
    monkeypatch.setenv("CHECKPOINT_INTERVAL_SECS", "0.5")
    from arroyo_tpu.config import reset_config

    reset_config()
    out_path = tmp_path / "out.jsonl"
    db_path = str(tmp_path / "controller.db")
    N = 40_000

    def make_prog():
        return (
            Stream.source("impulse", {"event_rate": 8000.0,
                                      "message_count": N,
                                      "event_time_interval_micros": 1000,
                                      "batch_size": 256})
            .watermark(max_lateness_micros=0)
            .map(lambda c: {"counter": c["counter"],
                            "bucket": c["counter"] % 5}, name="b")
            .key_by("bucket")
            .tumbling_aggregate(
                500 * 1000, [AggSpec(AggKind.COUNT, None, "cnt")])
            .sink("single_file", {"path": str(out_path)})
        )

    async def incarnation_one():
        sched = ProcessScheduler()
        ctrl = ControllerServer(sched, db_path=db_path)
        await ctrl.start()
        job_id = await ctrl.submit_job(
            make_prog(), checkpoint_url=f"file://{tmp_path}/ckpt",
            n_workers=1)
        await ctrl.wait_for_state(job_id, JobState.RUNNING, timeout=60)
        for _ in range(600):
            if (ctrl.jobs[job_id].last_successful_epoch or 0) >= 1:
                break
            await asyncio.sleep(0.05)
        assert (ctrl.jobs[job_id].last_successful_epoch or 0) >= 1
        orphan_pids = sched.workers_for_job(job_id)
        assert orphan_pids
        # CRASH: cancel the supervisor and drop the rpc server without
        # stopping workers or touching the scheduler
        ctrl.jobs[job_id].supervisor.cancel()
        await ctrl.rpc.stop()
        ctrl.store.close()
        return job_id, orphan_pids

    async def incarnation_two(job_id, orphan_pids):
        sched = ProcessScheduler()
        ctrl = ControllerServer(sched, db_path=db_path)
        await ctrl.start()  # resumes from the store
        try:
            assert job_id in ctrl.jobs, "job not re-adopted from store"
            state = await ctrl.wait_for_state(
                job_id, JobState.RUNNING, JobState.FINISHED, timeout=90)
            assert state in (JobState.RUNNING, JobState.FINISHED)
            # the first incarnation's workers must be gone (reaped or
            # self-terminated); pids must not linger running our worker
            for p in orphan_pids:
                pid = int(p.split("-", 1)[1])
                try:
                    with open(f"/proc/{pid}/cmdline", "rb") as f:
                        assert b"arroyo_tpu.worker.server" not in f.read()
                except OSError:
                    pass  # gone — good
            state = await ctrl.wait_for_state(job_id, JobState.FINISHED,
                                              timeout=120)
            # durable store converged too
            rows = ctrl.store.resumable()
            assert all(r.job_id != job_id for r in rows)
        finally:
            await sched.stop_workers(job_id)
            await ctrl.stop()
        return state

    try:
        job_id, orphans = asyncio.run(incarnation_one())
        state = asyncio.run(incarnation_two(job_id, orphans))
    finally:
        reset_config()
    assert state == JobState.FINISHED
    rows = [json.loads(line) for line in open(out_path)]
    assert sum(r["cnt"] for r in rows) == N
    assert len({r["bucket"] for r in rows}) == 5


def test_expired_ttl_job_settles_on_controller_restart(tmp_path):
    """A preview (ttl) job whose deadline passed while the controller
    was down must settle to Stopped on resume — not run forever (the
    API-side reaper died with the old process; the deadline lives in
    the durable store)."""
    from arroyo_tpu.controller.scheduler import InProcessScheduler

    db_path = str(tmp_path / "c.db")

    async def one():
        sched = InProcessScheduler()
        ctrl = ControllerServer(sched, db_path=db_path)
        await ctrl.start()
        prog = (
            Stream.source("impulse", {"event_rate": 50.0,
                                      "message_count": 10_000_000,
                                      "batch_size": 32})
            .map(lambda c: {"counter": c["counter"]}, name="m")
            .sink("blackhole", {})
        )
        jid = await ctrl.submit_job(
            prog, checkpoint_url=f"file://{tmp_path}/ckpt",
            ttl_secs=1.0)
        await ctrl.wait_for_state(jid, JobState.RUNNING, timeout=60)
        # crash without stopping the job; in-process workers die with
        # the process, so kill them too (leaving their grpc servers to
        # the GC raises unraisable-exception noise on loop close)
        ctrl.jobs[jid].supervisor.cancel()
        await sched.stop_workers(jid, force=True)
        await ctrl.rpc.stop()
        ctrl.store.close()
        return jid

    async def two(jid):
        await asyncio.sleep(1.2)  # deadline passes while "down"
        ctrl = ControllerServer(InProcessScheduler(), db_path=db_path)
        await ctrl.start()
        try:
            assert jid not in ctrl.jobs, "expired ttl job was resumed"
            rows = ctrl.store.resumable()
            assert all(r.job_id != jid for r in rows)
        finally:
            await ctrl.stop()

    jid = asyncio.run(one())
    asyncio.run(two(jid))


@pytest.mark.slow
def test_live_ttl_survives_controller_restart(tmp_path):
    """A ttl job restarted BEFORE its deadline resumes — and the new
    controller's supervisor still stops it when the deadline passes."""
    from arroyo_tpu.controller.scheduler import InProcessScheduler

    db_path = str(tmp_path / "c.db")

    async def one():
        sched = InProcessScheduler()
        ctrl = ControllerServer(sched, db_path=db_path)
        await ctrl.start()
        prog = (
            Stream.source("impulse", {"event_rate": 50.0,
                                      "message_count": 10_000_000,
                                      "batch_size": 32})
            .map(lambda c: {"counter": c["counter"]}, name="m")
            .sink("blackhole", {})
        )
        jid = await ctrl.submit_job(
            prog, checkpoint_url=f"file://{tmp_path}/ckpt",
            ttl_secs=6.0)
        await ctrl.wait_for_state(jid, JobState.RUNNING, timeout=60)
        ctrl.jobs[jid].supervisor.cancel()
        await sched.stop_workers(jid, force=True)
        await ctrl.rpc.stop()
        ctrl.store.close()
        return jid

    async def two(jid):
        ctrl = ControllerServer(InProcessScheduler(), db_path=db_path)
        await ctrl.start()
        try:
            assert jid in ctrl.jobs, "live ttl job not resumed"
            assert ctrl.jobs[jid].ttl_deadline is not None
            state = await ctrl.wait_for_state(
                jid, JobState.STOPPED, timeout=60)
            assert state == JobState.STOPPED, state
        finally:
            await ctrl.stop()

    jid = asyncio.run(one())
    asyncio.run(two(jid))


def test_rescaled_parallelism_survives_controller_restart(tmp_path):
    """rescale_job persists the updated program; a controller crash
    right after the rescale must resume the job at the NEW parallelism,
    not the submitted one."""
    from arroyo_tpu.controller.scheduler import InProcessScheduler

    db_path = str(tmp_path / "c.db")

    async def one():
        sched = InProcessScheduler()
        ctrl = ControllerServer(sched, db_path=db_path)
        await ctrl.start()
        prog = (
            Stream.source("impulse", {"event_rate": 4000.0,
                                      "message_count": 10_000_000,
                                      "event_time_interval_micros": 1000,
                                      "batch_size": 256})
            .watermark(max_lateness_micros=0)
            .map(lambda c: {"counter": c["counter"],
                            "bucket": c["counter"] % 5}, name="b")
            .key_by("bucket")
            .tumbling_aggregate(
                500 * 1000, [AggSpec(AggKind.COUNT, None, "cnt")],
                parallelism=1)
            .sink("blackhole", {})
        )
        jid = await ctrl.submit_job(
            prog, checkpoint_url=f"file://{tmp_path}/ckpt")
        await ctrl.wait_for_state(jid, JobState.RUNNING, timeout=60)
        for _ in range(400):  # need a checkpoint for the rescale stop
            if (ctrl.jobs[jid].last_successful_epoch or 0) >= 1:
                break
            await asyncio.sleep(0.05)
        agg_ops = [n.operator_id
                   for n in ctrl.jobs[jid].program.nodes()
                   if "aggregator" in n.operator_id]
        await ctrl.rescale_job(jid, {op: 2 for op in agg_ops})
        await ctrl.wait_for_state(jid, JobState.RUNNING, timeout=60)
        # crash
        ctrl.jobs[jid].supervisor.cancel()
        await sched.stop_workers(jid, force=True)
        await ctrl.rpc.stop()
        ctrl.store.close()
        return jid, agg_ops

    async def two(jid, agg_ops):
        ctrl = ControllerServer(InProcessScheduler(), db_path=db_path)
        await ctrl.start()
        try:
            assert jid in ctrl.jobs
            await ctrl.wait_for_state(jid, JobState.RUNNING, timeout=60)
            prog = ctrl.jobs[jid].program
            for op in agg_ops:
                assert prog.node(op).parallelism == 2, op
            await ctrl.stop_job(jid, checkpoint=False)
            await ctrl.wait_for_state(jid, JobState.STOPPED, timeout=60)
        finally:
            await ctrl.stop()

    import os
    os.environ["CHECKPOINT_INTERVAL_SECS"] = "0.5"
    from arroyo_tpu.config import reset_config

    reset_config()
    try:
        jid, agg_ops = asyncio.run(one())
        asyncio.run(two(jid, agg_ops))
    finally:
        os.environ.pop("CHECKPOINT_INTERVAL_SECS", None)
        reset_config()
