"""Sharded-by-default data plane (parallel/shuffle.py): on-device
co-located shuffle parity with the host route, the resharding invariant
(ensure_sharded counting), Collector integration at real parallelism,
and the mesh placement of hot join rings."""

import asyncio

import numpy as np
import pytest

from arroyo_tpu.native import partition_route
from arroyo_tpu.obs import perf
from arroyo_tpu.parallel import shuffle as shf
from arroyo_tpu.types import Batch, hash_columns

SEC = 1_000_000


def _keyed_batch(rng, n=4000, nkeys=300):
    keys = rng.integers(0, nkeys, n).astype(np.int64)
    kh = hash_columns([keys])
    return Batch(
        np.sort(rng.integers(0, 10 * SEC, n)).astype(np.int64),
        {"k": keys,
         "v": rng.standard_normal(n),
         "f32": rng.standard_normal(n).astype(np.float32),
         "flag": rng.random(n) < 0.5,
         "big": kh.copy(),  # u64 column: must survive bit-exact
         "i32": rng.integers(-100, 100, n).astype(np.int32)},
        kh, ("k",))


@pytest.mark.parametrize("nd", [2, 4, 8])
def test_device_route_matches_host_partition_route(rng, monkeypatch, nd):
    """The on-device all_to_all exchange must deliver, per destination,
    exactly the rows the host ``partition_route`` path delivers — same
    rows, same order, same dtypes (u64 bit-exact)."""
    monkeypatch.setenv("ARROYO_SHUFFLE_DEVICE", "on")
    b = _keyed_batch(rng)
    assert shf.device_shuffle_enabled(nd)
    before = perf.counter(shf.COLLECTIVES)
    parts = shf.DeviceShuffle(nd, op_id="t").route(b)
    assert parts is not None
    assert perf.counter(shf.COLLECTIVES) == before + 1
    got = dict(parts)
    _, order, bounds = partition_route(b.key_hash, nd)
    for d in range(nd):
        lo, hi = bounds[d], bounds[d + 1]
        if hi == lo:
            assert d not in got
            continue
        ref = b.select(order[lo:hi])
        sub = got[d]
        np.testing.assert_array_equal(sub.timestamp, ref.timestamp)
        np.testing.assert_array_equal(sub.key_hash, ref.key_hash)
        assert sub.key_cols == ref.key_cols
        assert list(sub.columns) == list(ref.columns)
        for c in ref.columns:
            assert sub.columns[c].dtype == ref.columns[c].dtype, c
            np.testing.assert_array_equal(sub.columns[c],
                                          ref.columns[c], err_msg=c)


def test_device_route_unsupported_batch_sticky_fallback(rng, monkeypatch):
    """Object (string) columns cannot ride the device transport: route
    returns None AND pins the host path for the edge's life, so the
    edge's output sharding spec never flips mid-stream."""
    monkeypatch.setenv("ARROYO_SHUFFLE_DEVICE", "on")
    keys = rng.integers(0, 50, 200).astype(np.int64)
    kh = hash_columns([keys])
    stringy = Batch(np.zeros(200, np.int64),
                    {"k": keys, "s": np.array(["x"] * 200, object)},
                    kh, ("k",))
    ds = shf.DeviceShuffle(4)
    assert ds.route(stringy) is None
    assert ds.route(_keyed_batch(rng)) is None  # sticky


def test_device_shuffle_enabled_gates(monkeypatch):
    monkeypatch.setenv("ARROYO_SHUFFLE_DEVICE", "on")
    monkeypatch.setenv("ARROYO_MESH", "auto")
    assert shf.device_shuffle_enabled(4)
    assert not shf.device_shuffle_enabled(3)   # non-power-of-two
    assert not shf.device_shuffle_enabled(16)  # beyond the 8-device mesh
    monkeypatch.setenv("ARROYO_MESH", "off")
    assert not shf.device_shuffle_enabled(4)   # mesh off = host topology
    monkeypatch.setenv("ARROYO_MESH", "auto")
    monkeypatch.setenv("ARROYO_SHUFFLE_DEVICE", "off")
    assert not shf.device_shuffle_enabled(4)
    # auto on the CPU backend stays off: device hop is pure overhead
    monkeypatch.setenv("ARROYO_SHUFFLE_DEVICE", "auto")
    assert not shf.device_shuffle_enabled(4)


def test_ensure_sharded_counts_reshards_only_on_mismatch():
    """Matched shardings pass through free; a mismatch counts ONE
    reshard; host arrays count as ingest staging, never reshard."""
    import jax

    sh_keys = shf.keys_sharding(4, "keys")
    sh_rep = shf.keys_sharding(4)
    x = np.arange(64, dtype=np.int64)
    r0 = perf.counter(shf.RESHARDS)
    i0 = perf.counter(shf.INGEST_TRANSFERS)
    d = shf.ensure_sharded(x, sh_keys)  # host -> device: ingest
    assert perf.counter(shf.RESHARDS) == r0
    assert perf.counter(shf.INGEST_TRANSFERS) == i0 + 1
    d2 = shf.ensure_sharded(d, sh_keys)  # already matching: free
    assert d2 is d
    assert perf.counter(shf.RESHARDS) == r0
    d3 = shf.ensure_sharded(d, sh_rep)  # mismatch: counted reshard
    assert perf.counter(shf.RESHARDS) == r0 + 1
    np.testing.assert_array_equal(np.asarray(jax.device_get(d3)), x)


def test_collector_device_shuffle_end_to_end(rng, monkeypatch):
    """A Collector with a co-located 4-way shuffle group routes through
    the device exchange (ARROYO_SHUFFLE_DEVICE=on) and downstream queues
    receive exactly the host path's rows; the sanitizer sees ONE stable
    sharding spec."""
    from arroyo_tpu.analysis.sanitizer import Sanitizer
    from arroyo_tpu.engine.context import Collector, OutQueue
    from arroyo_tpu.types import MessageKind

    monkeypatch.setenv("ARROYO_SHUFFLE_DEVICE", "on")
    b = _keyed_batch(rng, n=3000)

    async def run(device_on):
        monkeypatch.setenv("ARROYO_SHUFFLE_DEVICE",
                           "on" if device_on else "off")
        qs = [asyncio.Queue(maxsize=1000) for _ in range(4)]
        san = Sanitizer("test")
        coll = Collector([[OutQueue(queue=q) for q in qs]],
                         op_id="opX", sanitizer=san)
        await coll.collect(b)
        await coll.collect(b)  # second batch: spec must not flip
        out = []
        for q in qs:
            rows = []
            while not q.empty():
                msg = q.get_nowait()
                assert msg.kind == MessageKind.RECORD
                rows.append(msg.batch)
            out.append(rows)
        return out, san

    c0 = perf.counter(shf.COLLECTIVES)
    dev_out, san = asyncio.run(run(True))
    assert perf.counter(shf.COLLECTIVES) == c0 + 2
    assert san._edge_sharding == {("opX", 0, 0): "keys@4"}
    host_out, _ = asyncio.run(run(False))
    for d in range(4):
        assert len(dev_out[d]) == len(host_out[d])
        for db, hb in zip(dev_out[d], host_out[d]):
            np.testing.assert_array_equal(db.key_hash, hb.key_hash)
            for c in hb.columns:
                np.testing.assert_array_equal(db.columns[c],
                                              hb.columns[c])


def test_engine_parallel2_device_shuffle_same_rows(monkeypatch):
    """A real SQL pipeline at parallelism 2 (actual multi-destination
    SHUFFLE edges) emits identical rows with the co-located device
    shuffle on and off — and the device path actually ran."""
    from arroyo_tpu.connectors.memory import clear_sink, sink_output
    from arroyo_tpu.engine.engine import LocalRunner
    from arroyo_tpu.sql import plan_sql

    SQL = """
    CREATE TABLE nexmark WITH (
      connector = 'nexmark', event_rate = '1000000', num_events = '20000',
      rate_limited = 'false', batch_size = '2048',
      base_time_micros = '1700000000000000'
    );
    SELECT bid.auction as auction, TUMBLE(INTERVAL '2' SECOND) as window,
           count(*) AS num
    FROM nexmark WHERE bid is not null GROUP BY 1, 2
    """

    def run(mode):
        monkeypatch.setenv("ARROYO_SHUFFLE_DEVICE", mode)
        clear_sink("results")
        LocalRunner(plan_sql(SQL, parallelism=2)).run()
        return sorted(
            (int(a), int(w), int(n))
            for b in sink_output("results")
            for a, w, n in zip(b.columns["auction"],
                               b.columns["window_end"], b.columns["num"]))

    c0 = perf.counter(shf.COLLECTIVES)
    rows_dev = run("on")
    assert perf.counter(shf.COLLECTIVES) > c0, \
        "device shuffle never engaged at parallelism 2"
    rows_host = run("off")
    assert rows_dev and rows_dev == rows_host


def test_join_ring_mesh_placement(rng, monkeypatch):
    """Hot join-state partitions place their device rings across the
    mesh (partition p -> device p % nk) instead of funneling through
    chip 0; probes against mesh-placed rings stay bit-identical to the
    host probe."""
    import jax

    from arroyo_tpu.state.join_state import PartitionedJoinBuffer

    monkeypatch.setenv("ARROYO_DEVICE_JOIN", "on")
    monkeypatch.setenv("ARROYO_MESH", "auto")
    monkeypatch.setenv("ARROYO_JOIN_HOT_MIN_ROWS", "1")
    monkeypatch.setenv("ARROYO_JOIN_HOT_PARTITIONS", "8")
    buf = PartitionedJoinBuffer(n_partitions=8)
    n = 20_000
    keys = rng.integers(0, 5000, n).astype(np.int64)
    kh = hash_columns([keys])
    b = Batch(np.sort(rng.integers(0, 30 * SEC, n)).astype(np.int64),
              {"k": keys, "v": rng.integers(0, 99, n)}, kh, ("k",))
    for lo in range(0, n, 4096):
        buf.append(b.select(np.arange(lo, min(lo + 4096, n))))
    stats = buf.stats()
    assert stats["hot_partitions"] >= 2
    assert stats["ring_devices"] >= 2, stats
    devices = {str(p.dev_device) for p in buf.parts if p.dev is not None}
    assert len(devices) >= 2
    assert all(p.dev_device in jax.devices() for p in buf.parts
               if p.dev is not None)
    # probe parity: device rings on non-default chips answer exactly
    # like the host searchsorted probe
    probe = np.sort(rng.choice(kh, 500, replace=False))
    qidx_dev, gpos_dev = buf.probe_positions(probe, pre_sorted=True)
    monkeypatch.setenv("ARROYO_DEVICE_JOIN", "off")
    buf_host = PartitionedJoinBuffer(n_partitions=8)
    buf_host.append(b)
    qidx_h, gpos_h = buf_host.probe_positions(probe, pre_sorted=True)
    pairs_dev = sorted(zip(qidx_dev.tolist(), gpos_dev.tolist()))
    pairs_h = sorted(zip(qidx_h.tolist(), gpos_h.tolist()))
    assert pairs_dev == pairs_h
